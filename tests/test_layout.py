"""The declarative layout table (compute/layout.py).

Three layers:

- **Table goldens** — each model family's rules evaluated on
  representative leaf names/shapes must reproduce the hand-rolled specs
  they replaced (the PR-11 migration is behavior-preserving by
  construction; these pin it).
- **Cross-table lockstep** — the llama table's MoE rules equal the moe
  table's (one source of truth, two consumers).
- **Layout ↔ elastic round-trip** — ``fit_axis_shapes`` +
  ``reshard_state`` driven from the table across shrink/grow keep
  params byte-identical AND the shardcheck collective census identical
  before/after reshard; a seeded table mutation (dropping the fsdp
  axis from one rule) is caught as a census diff.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.compute import layout
from tensorflowonspark_tpu.compute.mesh import (
    batch_sharding,
    fit_axis_shapes,
    make_mesh,
    replicated,
)


# -- table goldens ----------------------------------------------------------


def spec_of(table, name, shape, axis_sizes=None):
    return layout.get_layout(table).spec(
        name, shape, axis_sizes or {"data": 2, "fsdp": 2, "model": 2}
    )


def test_llama_table_core_rules():
    # column-parallel projections
    assert spec_of("llama", "embed/embedding", (256, 128)) == P("fsdp", "model")
    assert spec_of("llama", "lm_head", (128, 256)) == P("fsdp", "model")
    assert spec_of("llama", "layer0/attn/q_proj/kernel", (128, 128)) == P(
        "fsdp", "model"
    )
    # row-parallel
    assert spec_of("llama", "layer0/attn/o_proj/kernel", (128, 128)) == P(
        "model", "fsdp"
    )
    assert spec_of("llama", "layer0/mlp/down_proj/kernel", (256, 128)) == P(
        "model", "fsdp"
    )
    # biases / norms replicated; router replicated
    assert spec_of("llama", "layer0/attn_norm/scale", (128,)) == P()
    assert spec_of("llama", "layer0/moe/router/kernel", (128, 8)) == P()
    # generic 2-D fallback
    assert spec_of("llama", "layer0/other/kernel", (128, 128)) == P(
        "fsdp", None
    )


def test_llama_table_lora_factors():
    # 'a' keeps the input half of the base pair, 'b' the output half
    assert spec_of("llama", "layer0/attn/q_proj/kernel/a", (128, 8)) == P(
        "fsdp", None
    )
    assert spec_of("llama", "layer0/attn/q_proj/kernel/b", (8, 128)) == P(
        None, "model"
    )
    assert spec_of("llama", "layer0/attn/o_proj/kernel/a", (128, 8)) == P(
        "model", None
    )
    assert spec_of("llama", "layer0/attn/o_proj/kernel/b", (8, 128)) == P(
        None, "fsdp"
    )
    # multi-LoRA banks: same halves behind the leading K slots dim
    assert spec_of("llama", "layer0/attn/q_proj/kernel/a", (4, 128, 8)) == P(
        None, "fsdp", None
    )
    assert spec_of("llama", "layer0/attn/q_proj/kernel/b", (4, 8, 128)) == P(
        None, None, "model"
    )


def test_llama_and_moe_tables_lockstep():
    # MoE expert banks: identical specs from both tables, any route
    for name, shape in [
        ("layer0/moe/w_gate", (4, 128, 256)),
        ("layer0/moe/w_up", (4, 128, 256)),
        ("layer0/moe/w_down", (4, 256, 128)),
    ]:
        assert spec_of("llama", name, shape) == spec_of("moe", name, shape)
        assert spec_of("moe", name, shape) == layout.expert_bank_spec(name)
    assert layout.expert_bank_spec("w_down") == P("expert", "model", "fsdp")
    assert layout.expert_bank_spec("w_gate") == P("expert", "fsdp", "model")


def test_bert_table_divisibility_fallthrough():
    sizes = {"fsdp": 2, "model": 2}
    assert spec_of("bert", "layer_0/attention/query/kernel", (128, 128),
                   sizes) == P("fsdp", "model")
    assert spec_of("bert", "layer_0/attention/attn_out/kernel", (128, 128),
                   sizes) == P("model", "fsdp")
    # odd output dim: the col rule falls through to the generic 2-D rule
    assert spec_of("bert", "pooler/query/kernel", (128, 3), sizes) == P(
        "fsdp", None
    )
    # odd both: replicated
    assert spec_of("bert", "head/kernel", (3, 3), sizes) == P()


def test_vit_table_per_dim_drop():
    sizes = {"fsdp": 2, "model": 2}
    assert spec_of("vit", "encoder/kernel", (128, 128), sizes) == P(
        "fsdp", "model"
    )
    # an indivisible head dim under model=2: drop dim 1 only
    assert spec_of("vit", "head/kernel", (128, 11), sizes) == P("fsdp", None)
    # unit extents drop too (the historical vit behavior)
    assert spec_of("vit", "encoder/kernel", (128, 128),
                   {"fsdp": 1, "model": 2}) == P(None, "model")


def test_resnet_unet_tables():
    sizes = {"fsdp": 4}
    assert spec_of("resnet", "conv/kernel", (3, 3, 64, 128), sizes) == P(
        None, None, None, "fsdp"
    )
    assert spec_of("resnet", "dense/kernel", (128, 10), sizes) == P(
        "fsdp", None
    )
    assert spec_of("resnet", "bn/scale", (64,), sizes) == P()
    assert spec_of("unet", "conv/kernel", (3, 3, 64, 128), sizes) == P(
        None, None, None, "fsdp"
    )
    assert spec_of("unet", "dense/kernel", (128, 10), sizes) == P()


def test_optimizer_table_zero_merge():
    """The ZeRO optimizer-state rules: the table's 'data' axis merges
    onto the param leaf's base spec, existing divisibility semantics
    dropping indivisible leaves back to the mirrored spec."""
    sizes = {"data": 2, "fsdp": 2, "model": 2}
    P_ = layout.optimizer_state_spec
    # moments over a column-parallel kernel: data prepends onto dim 0
    assert P_("0/mu/layer0/q_proj/kernel", (128, 128),
              P("fsdp", "model"), sizes) == P(("data", "fsdp"), "model")
    # replicated base (pure DP) -> plain data partition; masters and
    # momentum traces follow the same rule as moments
    assert P_("0/nu/w", (64, 32), P(), sizes) == P("data")
    assert P_("master/w", (64, 32), P(), sizes) == P("data")
    assert P_("0/trace/w", (64, 32), P(), sizes) == P("data")
    # the in-step gradient/update tensors share the layout
    assert P_("update/w", (64, 32), P(), sizes) == P("data")
    # indivisible leading dim: drop-to-replicated-across-data (the
    # mirrored base survives untouched)
    assert P_("0/mu/norm/scale", (9,), P(), sizes) == P()
    # a fully-dropped merge returns the base VERBATIM — the equality
    # consumers (make_step_fn's constraint no-op) key on
    assert P_("0/mu/w", (2, 8), P("fsdp", None), sizes) == P("fsdp", None)
    # Adam's scalar count: the explicit scalar rule, replicated
    assert P_("0/count", (), P(), sizes) == P()
    # data axis extent 1 (pure-FSDP mesh): the merge is inert
    assert P_("0/mu/w", (64, 32), P("fsdp", None), {"fsdp": 4}) == P(
        "fsdp", None
    )
    # undeclared fields mirror their base unchanged
    assert P_("0/whatever/w", (64, 32), P("fsdp", None), sizes) == P(
        "fsdp", None
    )


def test_optimizer_pattern_constant_lockstep():
    """The per-param-state regex consumed by train.state_shardings'
    explicit resolution must stay textually equal to the table rule
    (the table is a pure literal for the AST analyzer, so the string is
    duplicated — this is the drift gate)."""
    patterns = [r["pattern"] for r in layout.LAYOUT_TABLES["optimizer"]]
    assert layout.OPTIMIZER_PARAM_STATE_PATTERN in patterns


def test_role_helpers():
    assert layout.batch_spec(3) == P(("data", "fsdp"), None, None)
    assert layout.activation_spec("prompt") == P("data", None)
    x4 = jnp.zeros((2, 4, 2, 8))
    x3 = jnp.zeros((2, 4, 2))
    assert layout.decode_cache_spec(x4) == P("data", None, "model", None)
    assert layout.decode_cache_spec(x4, tp=False) == P(
        "data", None, None, None
    )
    assert layout.decode_cache_spec(x3) == P("data", None, "model")
    assert layout.serve_cache_spec(x4) == P(None, None, "model", None)
    assert layout.serve_cache_spec(jnp.zeros(())) == P()
    assert layout.fsdp_leaf_spec((4096, 31), 4) == P("fsdp", None)
    assert layout.fsdp_leaf_spec((31, 4096), 4) == P(None, "fsdp")
    assert layout.fsdp_leaf_spec((8,), 4) == P()  # tiny -> replicated


def test_tp_only_projection(mesh8):
    sh = layout.sharding(mesh8, P(("fsdp", "model"), None))
    assert layout.tp_only(mesh8, sh).spec == P("model", None)
    sh2 = layout.sharding(mesh8, P("fsdp", "model"))
    assert layout.tp_only(mesh8, sh2).spec == P(None, "model")


def test_unknown_table_and_missing_catchall():
    with pytest.raises(KeyError, match="unknown layout table"):
        layout.get_layout("nope")
    bare = layout.SpecLayout(
        "bare", ({"pattern": r"x", "spec": ("fsdp",)},)
    )
    with pytest.raises(ValueError, match="catch-all"):
        bare.spec("y", (4,))


# -- layout ↔ elastic round-trip with census equality -----------------------


def _toy_params():
    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 4)
    return {
        "embed": {"embedding": jax.random.normal(ks[0], (64, 32))},
        "layer0": {
            "q_proj": {"kernel": jax.random.normal(ks[1], (32, 64))},
            "o_proj": {"kernel": jax.random.normal(ks[2], (64, 32))},
            "norm": {"scale": jax.random.normal(ks[3], (32,))},
        },
    }


def _toy_step(params, batch):
    h = batch @ params["embed"]["embedding"]
    h = h @ params["layer0"]["q_proj"]["kernel"]
    h = h @ params["layer0"]["o_proj"]["kernel"]
    return jnp.sum(h * params["layer0"]["norm"]["scale"])


def _census_for(mesh, params, batch_shape):
    from tensorflowonspark_tpu.analysis import shardcheck as sc

    psh = layout.param_shardings(params, mesh, "llama")
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
    )
    batch = jax.ShapeDtypeStruct(batch_shape, jnp.float32)
    return sc.hlo_census(
        _toy_step,
        (abstract, batch),
        in_shardings=(psh, batch_sharding(mesh, len(batch_shape))),
        out_shardings=replicated(mesh),
    )


def test_layout_elastic_roundtrip_bytes_and_census():
    """Shrink 8→4 devices then grow back: params byte-identical, and the
    table-derived collective census identical before/after."""
    from tensorflowonspark_tpu.compute.elastic import reshard_state

    devices = jax.devices()[:8]
    spec = {"data": 2, "fsdp": -1, "model": 2}
    mesh_a = make_mesh(fit_axis_shapes(spec, 8), devices=devices)
    params = _toy_params()
    placed = jax.tree.map(
        jax.device_put, params, layout.param_shardings(params, mesh_a, "llama")
    )
    census_before = _census_for(mesh_a, params, (8, 64))

    # shrink to 4 devices: the elastic axis absorbs the change
    mesh_b = make_mesh(fit_axis_shapes(spec, 4), devices=devices[:4])
    shrunk = reshard_state(
        placed, layout.param_shardings(params, mesh_b, "llama")
    )
    # grow back to 8
    mesh_c = make_mesh(fit_axis_shapes(spec, 8), devices=devices)
    regrown = reshard_state(
        shrunk, layout.param_shardings(params, mesh_c, "llama")
    )

    flat_a = jax.tree.leaves(jax.tree.map(jax.device_get, placed))
    flat_c = jax.tree.leaves(jax.tree.map(jax.device_get, regrown))
    for a, c in zip(flat_a, flat_c):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))

    census_after = _census_for(mesh_c, params, (8, 64))
    assert census_before == census_after


def test_zero_state_roundtrip_bytes_and_census():
    """Shrink 8→4 devices then regrow with the FULL TrainState under
    the ZeRO optimizer rules (mixed-precision fp32 masters + bf16
    moments): every leaf byte-identical across the round trip, the
    moments/masters genuinely data-partitioned, the indivisible leaf
    dropped to replicated-across-data, and the table-derived collective
    census identical before/after."""
    from tensorflowonspark_tpu.analysis import shardcheck as sc
    from tensorflowonspark_tpu.compute import mixed_precision_adamw
    from tensorflowonspark_tpu.compute.elastic import reshard_state
    from tensorflowonspark_tpu.compute.train import (
        TrainState,
        make_step_fn,
        shard_state,
        state_shardings,
    )

    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16), _toy_params()
    )
    params["layer0"]["odd_bias"] = jnp.arange(9, dtype=jnp.bfloat16)
    tx = mixed_precision_adamw(1e-2)

    def loss_fn(p, batch):
        h = batch @ p["embed"]["embedding"].astype(jnp.float32)
        h = h @ p["layer0"]["q_proj"]["kernel"].astype(jnp.float32)
        h = h @ p["layer0"]["o_proj"]["kernel"].astype(jnp.float32)
        return jnp.sum(h * p["layer0"]["norm"]["scale"].astype(jnp.float32))

    devices = jax.devices()
    spec = {"data": -1, "model": 2}

    def placed_state(n):
        mesh = make_mesh(
            fit_axis_shapes(spec, n, elastic_axis="data"),
            devices=devices[:n],
        )
        psh = layout.param_shardings(params, mesh, "llama")
        return mesh, psh

    def census_for(mesh, psh, state):
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state
        )
        ssh = state_shardings(state, mesh, psh)
        step = make_step_fn(
            loss_fn, tx, mesh, param_shardings=psh, zero_sharding=True
        )
        return sc.hlo_census(
            step,
            (abstract, jax.ShapeDtypeStruct((8, 64), jnp.float32)),
            in_shardings=(ssh, batch_sharding(mesh, 2)),
            out_shardings=(ssh, replicated(mesh)),
            donate_argnums=(0,),
        )

    mesh_a, psh_a = placed_state(8)
    state = shard_state(TrainState.create(params, tx), mesh_a, psh_a)
    # the ZeRO placement is real: the master/moments of the big kernel
    # carry the data axis, the odd 9-element leaf dropped to mirrored
    master = state.opt_state.master
    master_spec = master["embed"]["embedding"].sharding.spec
    flat_axes = [
        ax
        for e in master_spec
        for ax in (e if isinstance(e, tuple) else (e,))
    ]
    assert "data" in flat_axes
    assert master["layer0"]["odd_bias"].sharding.spec == P()
    before = [
        np.asarray(x).tobytes()
        for x in jax.tree.leaves(jax.device_get(state))
    ]
    census_before = census_for(mesh_a, psh_a, state)

    mesh_b, psh_b = placed_state(4)
    shrunk = reshard_state(
        state, state_shardings(state, mesh_b, psh_b)
    )
    mesh_c, psh_c = placed_state(8)
    regrown = reshard_state(
        shrunk, state_shardings(shrunk, mesh_c, psh_c)
    )
    after = [
        np.asarray(x).tobytes()
        for x in jax.tree.leaves(jax.device_get(regrown))
    ]
    assert before == after
    assert census_before == census_for(mesh_c, psh_c, regrown)


def test_zero_knob_changes_the_census():
    """The zero_sharding knob's A/B is visible as a census diff on a
    data-carrying mesh — the delta tools/shardcheck_baseline.json
    commits for llama1b (top-level heads vs its zero_off section)."""
    from tensorflowonspark_tpu.analysis import shardcheck as sc
    from tensorflowonspark_tpu.compute.train import (
        TrainState,
        make_step_fn,
        state_shardings,
    )
    import optax

    mesh = make_mesh({"data": 2, "fsdp": 2, "model": 2})
    params = _toy_params()
    tx = optax.adamw(1e-3)
    psh = layout.param_shardings(params, mesh, "llama")
    state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params=jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
        ),
        opt_state=jax.eval_shape(
            tx.init,
            jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
            ),
        ),
    )
    batch = jax.ShapeDtypeStruct((8, 64), jnp.float32)
    censuses = {}
    for zero in (True, False):
        ssh = state_shardings(state, mesh, psh, zero_sharding=zero)
        step = make_step_fn(
            _toy_step, tx, mesh,
            param_shardings=psh, zero_sharding=zero,
        )
        censuses[zero] = sc.hlo_census(
            step,
            (state, batch),
            in_shardings=(ssh, batch_sharding(mesh, 2)),
            out_shardings=(ssh, replicated(mesh)),
            donate_argnums=(0,),
        )
    assert sc.diff_census(
        {"hlo": censuses[False]}, {"hlo": censuses[True]}
    ), "zero_sharding on vs off must change the collective census"


def test_seeded_layout_mutation_is_a_census_diff():
    """Dropping the fsdp axis from the col-parallel rule (the ISSUE's
    worked example of an accidental layout edit) changes the collective
    census — the regression shardcheck exists to catch."""
    from tensorflowonspark_tpu.analysis import shardcheck as sc

    mesh = make_mesh({"data": 2, "fsdp": 2, "model": 2})
    params = _toy_params()
    base = _census_for(mesh, params, (8, 64))

    mutated_rules = []
    for rule in layout.LAYOUT_TABLES["llama"]:
        if rule["spec"] == ("fsdp", "model"):
            rule = dict(rule, spec=(None, "model"))  # drop the fsdp axis
        mutated_rules.append(rule)
    mutated = layout.SpecLayout("llama-mutated", tuple(mutated_rules))

    psh = layout.param_shardings(params, mesh, mutated)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
    )
    cur = sc.hlo_census(
        _toy_step,
        (abstract, jax.ShapeDtypeStruct((8, 64), jnp.float32)),
        in_shardings=(psh, batch_sharding(mesh, 2)),
        out_shardings=replicated(mesh),
    )
    diff = sc.diff_census({"hlo": base}, {"hlo": cur})
    assert diff, "a dropped fsdp axis must change the census"


def test_param_shardings_matches_model_functions(mesh8):
    """The public model entry points ARE table lookups now — pin the
    delegation (llama here; the zoo suites cover the conv families)."""
    from tensorflowonspark_tpu.models.llama import llama_param_shardings

    params = _toy_params()
    via_model = llama_param_shardings(params, mesh8)
    via_table = layout.param_shardings(params, mesh8, "llama")
    assert all(
        jax.tree.leaves(jax.tree.map(lambda a, b: a == b, via_model, via_table))
    )
    assert (
        via_model["embed"]["embedding"].spec == P("fsdp", "model")
    )
