"""Windowed telemetry history (obs/history.py) + SLO burn-rate plane
(obs/slo.py).

History is the read substrate both the SLO evaluator and the future
autotune controller consume, so its selector semantics (None = sum all
label sets, dict = label-subset filter, str = exact rendered key),
windowing, and histogram interpolation are pinned here with explicit
timestamps — no sleeps, no wall-clock flake. The SLO tests pin the
multi-window breach contract: BOTH windows must burn, breaches are
rising-edge counted, an empty window never false-fires, and the onset
lands in the flight recorder.
"""

import time

import pytest

from tensorflowonspark_tpu.obs import flightrec
from tensorflowonspark_tpu.obs.history import History
from tensorflowonspark_tpu.obs.registry import Registry
from tensorflowonspark_tpu.obs.slo import (
    SLO,
    SLOEvaluator,
    default_serving_slos,
    router_slos,
)

T0 = 1_000_000.0  # fixed epoch base: every test stamps scrapes itself


# -- History: selectors, windows, math ---------------------------------------


def test_counter_selector_semantics():
    reg = Registry()
    c = reg.counter("jobs_total")
    c.inc(2, route="a")
    c.inc(3, route="b")
    hist = History()
    hist.scrape_registry(reg, t=T0)
    # None sums every label set (Prometheus-style)
    assert hist.delta("jobs_total", None, window_s=None) == 5.0
    # dict is a label-SUBSET filter
    assert hist.delta("jobs_total", {"route": "a"}, window_s=None) == 2.0
    # str is the exact rendered series key
    keys = hist.labels_of("jobs_total")
    assert len(keys) == 2
    by_key = {
        k: hist.delta("jobs_total", k, window_s=None) for k in keys
    }
    assert sorted(by_key.values()) == [2.0, 3.0]
    assert hist.delta("jobs_total", {"route": "nope"}, window_s=None) == 0.0
    assert hist.names() == ["jobs_total"]


def test_delta_windows_by_scrape_time():
    reg = Registry()
    c = reg.counter("events_total")
    hist = History()
    c.inc(4)
    hist.scrape_registry(reg, t=T0)
    c.inc(6)
    hist.scrape_registry(reg, t=T0 + 100)
    # trailing 60s from T0+130 sees only the second scrape's delta
    assert hist.delta("events_total", window_s=60.0, now=T0 + 130) == 6.0
    assert hist.delta("events_total", window_s=None) == 10.0
    # a window past every point is empty, not an error
    assert hist.delta("events_total", window_s=60.0, now=T0 + 1000) == 0.0


def test_rate_needs_two_points_and_divides_by_span():
    reg = Registry()
    c = reg.counter("ticks_total")
    hist = History()
    c.inc(5)
    hist.scrape_registry(reg, t=T0)
    assert hist.rate("ticks_total", window_s=None) is None
    c.inc(5)
    hist.scrape_registry(reg, t=T0 + 10)
    assert hist.rate("ticks_total", window_s=None) == pytest.approx(0.5)


def test_histogram_fraction_le_interpolates_and_percentile():
    reg = Registry()
    h = reg.histogram("lat_seconds", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0, 3.0):
        h.observe(v)
    hist = History()
    hist.scrape_registry(reg, t=T0)
    q = dict(window_s=None)
    # exact bucket edge: no interpolation
    assert hist.fraction_le("lat_seconds", 2.0, **q) == pytest.approx(0.5)
    # mid-bucket: linear within the straddling (2, 4] bucket
    assert hist.fraction_le("lat_seconds", 3.0, **q) == pytest.approx(0.75)
    # below the first edge interpolates from zero
    assert hist.fraction_le("lat_seconds", 0.5, **q) == pytest.approx(0.125)
    assert hist.percentile("lat_seconds", 0.5, **q) == pytest.approx(2.0)
    assert hist.percentile("lat_seconds", 1.0, **q) == pytest.approx(4.0)
    # observations above the top finite bucket clamp to it
    h.observe(10.0)
    hist.scrape_registry(reg, t=T0 + 1)
    assert hist.percentile("lat_seconds", 1.0, **q) == pytest.approx(4.0)
    assert hist.fraction_le("lat_seconds", 4.0, **q) == pytest.approx(0.8)
    with pytest.raises(ValueError):
        hist.percentile("lat_seconds", 1.5, **q)


def test_fraction_le_none_without_observations():
    hist = History()
    assert hist.fraction_le("nope_seconds", 1.0, window_s=None) is None
    reg = Registry()
    reg.histogram("idle_seconds", buckets=(1.0,))
    hist.scrape_registry(reg, t=T0)
    # a histogram with zero in-window observations is "no data", not 0%
    assert hist.fraction_le("idle_seconds", 1.0, window_s=None) is None


def test_ring_capacity_bounds_memory_not_lifetime_count():
    reg = Registry()
    c = reg.counter("spins_total")
    hist = History(capacity=4)
    for i in range(10):
        c.inc()
        hist.scrape_registry(reg, t=T0 + i)
    assert len(hist.series("spins_total", "")) == 4
    assert hist.stats() == {"series": 1, "points": 10, "capacity": 4}
    # delta over the full window only sees retained points — eviction
    # shrinks the window, it does not corrupt the sums
    assert hist.delta("spins_total", window_s=None) == 4.0


def test_to_artifact_filters_names_and_is_json_shaped():
    reg = Registry()
    reg.counter("keep_total").inc(3)
    reg.gauge("drop_me").set(1.0)
    hist = History(source="unit")
    hist.scrape_registry(reg, t=T0)
    art = hist.to_artifact(names=("keep_total",))
    assert art["history_version"] == 1
    assert art["source"] == "unit"
    assert [s["name"] for s in art["series"]] == ["keep_total"]
    (s,) = art["series"]
    assert s["kind"] == "counter"
    assert s["points"][0]["value"] == 3.0
    assert s["points"][0]["delta"] == 3.0


def test_record_families_driver_scrape_path():
    """The MetricsAggregator path: parsed Prometheus families, joined
    with per-node labels, deltas computed against the previous point."""
    hist = History()
    fam = {
        "pulls_total": {
            "type": "counter",
            "samples": {("pulls_total", (("shard", "0"),)): 5.0},
        }
    }
    hist.record_families(fam, extra_labels={"node": "3"}, t=T0)
    fam["pulls_total"]["samples"][("pulls_total", (("shard", "0"),))] = 9.0
    hist.record_families(fam, extra_labels={"node": "3"}, t=T0 + 10)
    assert hist.delta(
        "pulls_total", {"node": "3", "shard": "0"}, window_s=None
    ) == 9.0
    # second point's delta is vs the first, not vs zero
    pts = hist.series("pulls_total", {"node": "3", "shard": "0"})
    assert [e["delta"] for _, e in pts] == [5.0, 4.0]


def test_record_families_histogram_regrouping():
    hist = History()
    fam = {
        "wait_seconds": {
            "type": "histogram",
            "samples": {
                ("wait_seconds_bucket", (("le", "1.0"),)): 2.0,
                ("wait_seconds_bucket", (("le", "+Inf"),)): 3.0,
                ("wait_seconds_sum", ()): 4.5,
                ("wait_seconds_count", ()): 3.0,
            },
        }
    }
    hist.record_families(fam, t=T0)
    (pt,) = [e for _, e in hist.series("wait_seconds", "")]
    assert pt["le"] == [1.0]
    assert pt["buckets"] == [2]
    assert pt["count"] == 3 and pt["sum"] == 4.5
    assert pt["delta_count"] == 3
    # 2 of 3 observations <= 1.0
    assert hist.fraction_le("wait_seconds", 1.0, window_s=None) == (
        pytest.approx(2 / 3)
    )


# -- SLO declarations ---------------------------------------------------------


def test_slo_declaration_validation():
    with pytest.raises(ValueError, match="kind"):
        SLO(name="x", kind="vibes", metric="m")
    with pytest.raises(ValueError, match="objective"):
        SLO(name="x", kind="latency", metric="m")
    with pytest.raises(ValueError, match="total_metric"):
        SLO(name="x", kind="error_rate", metric="m")
    with pytest.raises(ValueError, match="budget"):
        SLO(name="x", kind="latency", metric="m", objective=1.0, budget=1.5)
    dup = SLO(name="x", kind="latency", metric="m", objective=1.0)
    with pytest.raises(ValueError, match="duplicate"):
        SLOEvaluator((dup, dup), History())


def test_builtin_slo_sets_are_valid_and_distinct():
    serving = default_serving_slos()
    routed = router_slos(latency_objective_s=2.0)
    for slos in (serving, routed):
        names = [s.name for s in slos]
        assert len(set(names)) == len(names)
    assert {s.kind for s in routed} == {"latency", "availability"}


# -- SLO evaluation: multi-window burn, rising edge ---------------------------


def _latency_evaluator(buckets=(1.0, 2.0)):
    """A 1s-objective latency SLO with burn thresholds 5x fast / 2.5x
    slow over a 10% budget — breach needs >= 50% of fast-window
    observations slow AND >= 25% of slow-window ones."""
    reg = Registry()
    h = reg.histogram("req_seconds", buckets=buckets)
    hist = History()
    slo = SLO(
        name="lat",
        kind="latency",
        metric="req_seconds",
        objective=1.0,
        budget=0.1,
        fast_window_s=60.0,
        slow_window_s=300.0,
        fast_burn=5.0,
        slow_burn=2.5,
    )
    ev = SLOEvaluator((slo,), hist, registry=reg)
    return reg, h, hist, ev


def test_empty_window_never_false_fires():
    _reg, _h, _hist, ev = _latency_evaluator()
    (v,) = ev.evaluate(now=T0)
    assert not v.breached
    assert v.burn_fast == 0.0 and v.burn_slow == 0.0
    assert v.bad_fraction_fast is None
    assert ev.breaching() == []


def test_latency_breach_is_rising_edge_counted(tmp_path):
    reg, h, hist, ev = _latency_evaluator()
    rec = flightrec.install(str(tmp_path / "rec.json"), registry=reg)
    try:
        breach_count = lambda: reg.counter("slo_breaches_total").value(
            slo="lat"
        )
        # clean leg: all observations under the objective
        for _ in range(10):
            h.observe(0.5)
        hist.scrape_registry(reg, t=T0)
        (v,) = ev.evaluate(now=T0)
        assert not v.breached and breach_count() == 0.0

        # half the fast window goes slow: burn hits exactly 5x fast
        # (10/20 bad over a 10% budget) and 2.5x+ slow
        for _ in range(10):
            h.observe(1.5)
        hist.scrape_registry(reg, t=T0 + 10)
        (v,) = ev.evaluate(now=T0 + 10)
        assert v.breached
        assert v.burn_fast == pytest.approx(5.0)
        assert ev.breaching() == ["lat"]
        assert breach_count() == 1.0

        # still breaching: the counter counts ONSETS, not cycles
        (v,) = ev.evaluate(now=T0 + 11)
        assert v.breached and breach_count() == 1.0
        ev_names = [
            e for e in rec.snapshot("t")["events"]
            if e["kind"] == "slo_breach"
        ]
        assert len(ev_names) == 1
        assert ev_names[0]["slo"] == "lat"
        assert ev_names[0]["slo_kind"] == "latency"

        # recovery: a clean fast window (old points age out) clears it
        for _ in range(30):
            h.observe(0.5)
        hist.scrape_registry(reg, t=T0 + 90)
        (v,) = ev.evaluate(now=T0 + 120)
        assert not v.breached and ev.breaching() == []
        assert breach_count() == 1.0

        # a second onset counts again
        for _ in range(40):
            h.observe(1.5)
        hist.scrape_registry(reg, t=T0 + 125)
        (v,) = ev.evaluate(now=T0 + 125)
        assert v.breached and breach_count() == 2.0
    finally:
        rec.stop()
        flightrec._recorder = None


def test_breach_requires_both_windows():
    """A spike confined to the fast window (slow window diluted under
    its threshold) must NOT breach — the slow window is the blip
    filter."""
    reg, h, hist, ev = _latency_evaluator()
    # 280s of clean history dominates the slow window
    for _ in range(90):
        h.observe(0.5)
    hist.scrape_registry(reg, t=T0)
    # then a 100%-slow burst inside the fast window only
    for _ in range(10):
        h.observe(1.5)
    hist.scrape_registry(reg, t=T0 + 280)
    (v,) = ev.evaluate(now=T0 + 280)
    assert v.burn_fast == pytest.approx(10.0)  # 10/10 bad / 0.1
    assert v.burn_slow == pytest.approx(1.0)  # 10/100 bad / 0.1
    assert not v.breached


def test_availability_kind_counts_sheds_against_offered_load():
    reg = Registry()
    shed = reg.counter("shed_total")
    reqs = reg.counter("requests_total")
    hist = History()
    slo = SLO(
        name="avail",
        kind="availability",
        metric="shed_total",
        total_metric="requests_total",
        budget=0.1,
        fast_window_s=60.0,
        slow_window_s=300.0,
        fast_burn=5.0,
        slow_burn=2.5,
    )
    ev = SLOEvaluator((slo,), hist, registry=reg)
    # 5 sheds over 45 admitted = 10% of OFFERED load (45+5): burn 1.0
    shed.inc(5)
    reqs.inc(45)
    hist.scrape_registry(reg, t=T0)
    (v,) = ev.evaluate(now=T0)
    assert v.burn_fast == pytest.approx(1.0)
    assert not v.breached
    # 30 sheds / 30 admitted = 50% bad: evaluated once the clean
    # scrape has aged out of the fast window, burn is 5x fast and
    # 35/110 = 3.2x slow — both over threshold
    shed.inc(30)
    reqs.inc(30)
    hist.scrape_registry(reg, t=T0 + 70)
    (v,) = ev.evaluate(now=T0 + 100)
    assert v.burn_fast == pytest.approx(5.0)
    assert v.breached


def test_statusz_and_burn_gauges_surface():
    reg, h, hist, ev = _latency_evaluator()
    for _ in range(4):
        h.observe(1.5)
    hist.scrape_registry(reg, t=T0)
    ev.evaluate(now=T0)
    st = ev.statusz()
    assert st["evaluations"] == 1
    assert st["breaching"] == ["lat"]
    (row,) = st["slos"]
    assert row["slo"] == "lat" and row["breached"] is True
    assert row["budget"] == 0.1 and row["objective"] == 1.0
    # the burn gauges are exported per window, scrapeable mid-incident
    g = reg.gauge("slo_burn_rate")
    assert g.value(slo="lat", window="fast") == pytest.approx(10.0)
    assert g.value(slo="lat", window="slow") == pytest.approx(10.0)
    assert ev.last_verdicts()[0].as_dict() == row


def test_history_scrape_roundtrip_wallclock():
    """One un-stamped scrape (real time.time()) — the default path
    serve_model's pump uses — lands queryable within a trailing
    window."""
    reg = Registry()
    reg.counter("live_total").inc(7)
    hist = History()
    n = hist.scrape_registry(reg)
    assert n == 1
    assert hist.delta("live_total", window_s=60.0) == 7.0
    assert time.time() - hist.series("live_total", "")[0][0] < 5.0
