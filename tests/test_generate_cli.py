"""tools/generate_text: decode CLI over a checkpointed Llama."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from tensorflowonspark_tpu.models.llama import Llama, LlamaConfig, generate
from tensorflowonspark_tpu.tools.generate_text import main


def _tiny_checkpoint(tmp_path):
    import optax

    from tensorflowonspark_tpu.compute import TrainState
    from tensorflowonspark_tpu.compute.checkpoint import CheckpointManager

    cfg = LlamaConfig.tiny(dtype=jnp.float32, remat=False)
    model = Llama(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    state = TrainState.create(params, optax.sgd(0.1))
    ckpt_dir = str(tmp_path / "ckpt")
    with CheckpointManager(ckpt_dir, async_save=False) as mgr:
        mgr.save(3, state, force=True)
    return cfg, model, params, ckpt_dir


def test_cli_decodes_mixed_length_prompts(tmp_path):
    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    prompts = [[1, 2, 3], [4, 5, 6, 7, 8]]
    pfile = tmp_path / "prompts.jsonl"
    pfile.write_text(
        "".join(json.dumps({"tokens": p}) + "\n" for p in prompts)
    )
    ofile = tmp_path / "out.jsonl"

    rc = main(
        [
            "--checkpoint", ckpt_dir,
            "--model", "tiny",
            # pin the CLI's compute dtype to fp32 (tiny() defaults to
            # bf16) so the exact-equality comparison below is stable
            "--config-overrides", '{"remat": false, "dtype": "float32"}',
            "--prompts", str(pfile),
            "--output", str(ofile),
            "--max-new-tokens", "6",
            "--seed", "0",
        ]
    )
    assert rc == 0
    rows = [json.loads(l) for l in ofile.read_text().splitlines()]
    assert len(rows) == 2

    # row-for-row equal to the library call on the same padded batch
    padded = np.zeros((2, 5), np.int32)
    padded[0, :3] = prompts[0]
    padded[1] = prompts[1]
    key = jax.random.split(jax.random.PRNGKey(0))[1]
    ref = np.asarray(
        generate(
            model,
            params,
            jnp.asarray(padded),
            max_new_tokens=6,
            rng=key,
            prompt_lengths=jnp.asarray([3, 5]),
        )
    )
    for i in range(2):
        assert rows[i]["tokens"] == ref[i].tolist()


def test_cli_mesh_sharded_decode_matches_unsharded(tmp_path):
    """--mesh 'data=4,model=2' decodes on the 8-device virtual mesh and
    must emit exactly the tokens the unsharded CLI run emits."""
    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [2, 9], [7, 7, 7, 7]]
    pfile = tmp_path / "prompts.jsonl"
    pfile.write_text(
        "".join(json.dumps({"tokens": p}) + "\n" for p in prompts)
    )

    outs = {}
    for label, extra in (
        ("plain", []),
        ("mesh", ["--mesh", "data=4,model=2"]),
    ):
        ofile = tmp_path / f"out_{label}.jsonl"
        rc = main(
            [
                "--checkpoint", ckpt_dir,
                "--model", "tiny",
                "--config-overrides", '{"remat": false, "dtype": "float32"}',
                "--prompts", str(pfile),
                "--output", str(ofile),
                "--max-new-tokens", "6",
                "--batch-size", "4",
                "--seed", "0",
                *extra,
            ]
        )
        assert rc == 0
        outs[label] = [
            json.loads(l)["tokens"] for l in ofile.read_text().splitlines()
        ]
    assert len(outs["mesh"]) == 4
    assert outs["mesh"] == outs["plain"]


def test_cli_speculative_matches_plain(tmp_path):
    """--draft-checkpoint switches to speculative decoding; output must
    be byte-identical to the plain greedy CLI run (self-draft here —
    the exactness contract holds for any draft)."""
    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 9]]
    pfile = tmp_path / "prompts.jsonl"
    pfile.write_text(
        "".join(json.dumps({"tokens": p}) + "\n" for p in prompts)
    )
    outs = {}
    for label, extra in (
        ("plain", []),
        (
            "spec",
            [
                "--draft-checkpoint", ckpt_dir,
                "--draft-model", "tiny",
                "--draft-config-overrides",
                '{"remat": false, "dtype": "float32"}',
                "--spec-k", "3",
            ],
        ),
    ):
        ofile = tmp_path / f"out_{label}.jsonl"
        rc = main(
            [
                "--checkpoint", ckpt_dir,
                "--model", "tiny",
                "--config-overrides", '{"remat": false, "dtype": "float32"}',
                "--prompts", str(pfile),
                "--output", str(ofile),
                "--max-new-tokens", "7",
                "--batch-size", "3",
                *extra,
            ]
        )
        assert rc == 0
        outs[label] = ofile.read_text()
    assert outs["spec"] == outs["plain"]


def test_cli_eos_trims_output(tmp_path):
    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    pfile = tmp_path / "prompts.jsonl"
    pfile.write_text(json.dumps({"tokens": [1, 2, 3, 4]}) + "\n")
    ofile = tmp_path / "out.jsonl"

    # find a token the greedy decode actually emits, use it as EOS
    key = jax.random.split(jax.random.PRNGKey(0))[1]
    ref = np.asarray(
        generate(
            model, params, jnp.asarray([[1, 2, 3, 4]], np.int32),
            max_new_tokens=6, rng=key,
        )
    )[0]
    eos = int(ref[2])

    rc = main(
        [
            "--checkpoint", ckpt_dir,
            "--model", "tiny",
            "--config-overrides", '{"remat": false, "dtype": "float32"}',
            "--prompts", str(pfile),
            "--output", str(ofile),
            "--max-new-tokens", "6",
            "--eos-id", str(eos),
        ]
    )
    assert rc == 0
    (row,) = [json.loads(l) for l in ofile.read_text().splitlines()]
    assert row["tokens"][-1] == eos
    assert eos not in row["tokens"][:-1]
    assert len(row["tokens"]) <= 6


def _post(port, path, payload):
    """POST JSON to the ephemeral test server; returns (status, body)
    with HTTP errors surfaced as their JSON bodies, not tracebacks."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_serve_model_generate_mesh_and_draft(tmp_path):
    """/generate with --gen-mesh AND --draft-checkpoint together: the
    TP/DP-sharded speculative server must return exactly the plain
    library decode's tokens."""
    import threading

    from tensorflowonspark_tpu.tools import serve_model

    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    server = serve_model.make_server(
        None,
        port=0,
        gen=dict(
            checkpoint=ckpt_dir,
            model="tiny",
            config_overrides='{"remat": false, "dtype": "float32"}',
            width=8,
            batch_size=4,
            max_new_tokens=5,
            mesh="data=4,model=2",
            draft_checkpoint=ckpt_dir,
            draft_model="tiny",
            draft_config_overrides='{"remat": false, "dtype": "float32"}',
            spec_k=2,
        ),
    )
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        code, body = _post(
            port, "/generate", {"prompts": [[1, 2, 3], [4, 5, 6, 7, 8]]}
        )
        assert code == 200, body
        comps = body["completions"]
        padded = np.zeros((2, 8), np.int32)
        padded[0, :3] = [1, 2, 3]
        padded[1, :5] = [4, 5, 6, 7, 8]
        ref = np.asarray(
            generate(
                model, params, jnp.asarray(padded), max_new_tokens=5,
                prompt_lengths=jnp.asarray([3, 5]),
            )
        )
        assert comps == ref.tolist()
    finally:
        server.shutdown()


def test_serve_model_generate_request_coalescing(tmp_path):
    """--gen-batch-window: concurrent /generate requests share ONE
    decode call (the batcher lingers collecting them), every client
    gets its own correct slice, and a bad prompt in a shared batch
    fails alone without poisoning its neighbors."""
    import threading

    from tensorflowonspark_tpu.tools import serve_model

    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    server = serve_model.make_server(
        None,
        port=0,
        gen=dict(
            checkpoint=ckpt_dir,
            model="tiny",
            config_overrides='{"remat": false, "dtype": "float32"}',
            width=8,
            batch_size=8,
            max_new_tokens=4,
            batch_window=0.3,
        ),
    )
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    batcher = server.RequestHandlerClass.gen_batcher
    assert batcher is not None
    try:
        # prime the compile so the coalescing window isn't eaten by it
        code, _ = _post(port, "/generate", {"prompts": [[1, 2]]})
        assert code == 200
        calls_after_prime = batcher.decode_calls

        prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
        results: dict[int, tuple] = {}

        def fire(i):
            results[i] = _post(
                port, "/generate", {"prompts": [prompts[i]]}
            )

        threads = [
            threading.Thread(target=fire, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        for i in range(6):
            code, body = results[i]
            assert code == 200, body
            ref = np.asarray(
                generate(
                    model,
                    params,
                    jnp.asarray([prompts[i]], np.int32),
                    max_new_tokens=4,
                )
            )
            assert body["completions"] == ref.tolist(), i
        # 6 near-simultaneous requests coalesce into very few decodes
        # (typically 1: the worker takes the first and lingers 300ms
        # for the rest); allow slack for scheduling jitter
        assert batcher.decode_calls - calls_after_prime <= 3

        # error isolation: a too-long prompt shares a window with a
        # valid one; only the guilty request 400s
        out: dict[str, tuple] = {}
        t_bad = threading.Thread(
            target=lambda: out.__setitem__(
                "bad", _post(port, "/generate", {"prompts": [[1] * 9]})
            )
        )
        t_ok = threading.Thread(
            target=lambda: out.__setitem__(
                "ok", _post(port, "/generate", {"prompts": [[4, 5]]})
            )
        )
        t_bad.start(); t_ok.start(); t_bad.join(); t_ok.join()
        assert out["bad"][0] == 400
        assert out["ok"][0] == 200
    finally:
        server.shutdown()


def test_serve_model_continuous_engine(tmp_path):
    """--gen-engine continuous: /generate rides the slot-based engine.
    Concurrent requests interleave in one decode loop; each completion
    still matches its solo generate() output, and the fixed-path-only
    options are rejected at startup."""
    import threading

    from tensorflowonspark_tpu.tools import serve_model

    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    gen = dict(
        checkpoint=ckpt_dir,
        model="tiny",
        config_overrides='{"remat": false, "dtype": "float32"}',
        width=8,
        batch_size=3,
        max_new_tokens=5,
        engine="continuous",
        prefill_chunk=4,
        prefix_cache=8,
    )
    server = serve_model.make_server(None, port=0, gen=gen)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9], [2, 4], [6]]
        results: dict[int, tuple] = {}

        def fire(i):
            results[i] = _post(
                port, "/generate", {"prompts": [prompts[i]]}
            )

        threads = [
            threading.Thread(target=fire, args=(i,))
            for i in range(len(prompts))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()
        for i, p in enumerate(prompts):
            code, body = results[i]
            assert code == 200, body
            want = np.asarray(
                generate(model, params, jnp.asarray([p], jnp.int32), 5)
            )[0].tolist()
            assert body["completions"] == [want], (i, body, want)
        assert server.gen_engine.admitted == len(prompts)

        # multi-row request fans out engine-side
        code, body = _post(
            port, "/generate", {"prompts": [[1, 2], [3, 4, 5]]}
        )
        assert code == 200
        for row, p in zip(body["completions"], [[1, 2], [3, 4, 5]]):
            want = np.asarray(
                generate(model, params, jnp.asarray([p], jnp.int32), 5)
            )[0].tolist()
            assert row == want

        # chunked mode isn't width-bucket-capped: a 9-token prompt
        # (over the 8-wide bucket) decodes fine...
        code, body = _post(port, "/generate", {"prompts": [[1] * 9]})
        assert code == 200, body
        # ...but KV capacity still rejects as a 400
        code, body = _post(port, "/generate", {"prompts": [[1] * 127]})
        assert code == 400 and "max_seq_len" in body["error"]

        # per-request stop sequences trim the completion
        full = np.asarray(
            generate(model, params, jnp.asarray([[2, 4]], jnp.int32), 5)
        )[0].tolist()
        code, body = _post(
            port, "/generate",
            {"prompts": [[2, 4]], "stop": [full[1:3]]},
        )
        assert code == 200
        assert body["completions"] == [full[:1]]

        # per-request sampling truncation: top_k=1 is argmax at every
        # step, so even at temperature 0.9 it matches the greedy decode
        code, body = _post(
            port, "/generate",
            {"prompts": [[2, 4]], "temperature": 0.9, "top_k": 1},
        )
        assert code == 200, body
        assert body["completions"] == [full]
        # min_p ~ 1 keeps only the most likely token -> greedy as well
        code, body = _post(
            port, "/generate",
            {"prompts": [[2, 4]], "temperature": 0.9, "min_p": 0.9999},
        )
        assert code == 200, body
        assert body["completions"] == [full]
        # invalid truncation params are a 400, engine-validated
        code, body = _post(
            port, "/generate", {"prompts": [[2, 4]], "top_p": 0}
        )
        assert code == 400 and "top_p" in body["error"]
        code, body = _post(
            port, "/generate", {"prompts": [[2, 4]], "min_p": 1.5}
        )
        assert code == 400 and "min_p" in body["error"]

        # scheduler observability
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats"
        ) as r:
            stats = json.loads(r.read())
        assert stats["mode"] == "continuous"
        assert stats["slots"] == 3
        # +2 multi-row, +1 over-width, +1 stop-sequence, +1 top_k=1,
        # +1 min_p request (the rejected top_p/min_p never admit)
        assert stats["admitted"] == len(prompts) + 6
        assert stats["steps"] > 0 and not stats["closed"]
        # the CLI-wired prefix cache is live and accounted in /stats
        assert stats["prefix_cache_entries"] > 0
        assert stats["prefix_hits"] + stats["prefix_misses"] > 0

        # seeded sampling: "seed" makes an n>1 sampled request fully
        # reproducible (rows derive seed+i -> distinct completions),
        # independent of everything already decoded on this engine
        req_body = {
            "prompts": [[1, 2]], "temperature": 0.9, "n": 2, "seed": 42,
        }
        code, body1 = _post(port, "/generate", req_body)
        assert code == 200, body1
        code, body2 = _post(port, "/generate", req_body)
        assert code == 200
        assert body1["completions"] == body2["completions"]
        assert body1["completions"][0][0] != body1["completions"][0][1]

        # repetition penalties ride per-request too: a strong
        # frequency_penalty yields a repeat-free completion; bad values
        # are a 400
        code, body = _post(
            port, "/generate",
            {"prompts": [[1, 2]], "frequency_penalty": 2.0},
        )
        assert code == 200, body
        toks = body["completions"][0]
        assert len(set(toks)) == len(toks), toks
        code, body = _post(
            port, "/generate",
            {"prompts": [[1, 2]], "presence_penalty": 9.0},
        )
        assert code == 400 and "presence_penalty" in body["error"]

        # logit_bias in the OpenAI wire format (string keys): +100
        # forces the token at every step incl. the first
        code, body = _post(
            port, "/generate",
            {"prompts": [[1, 2]], "logit_bias": {"5": 100.0}},
        )
        assert code == 200, body
        assert body["completions"][0] == [5] * 5
        code, body = _post(
            port, "/generate",
            {"prompts": [[1, 2]], "logit_bias": {"5": 200.0}},
        )
        assert code == 400 and "logit_bias" in body["error"]

        # streaming: NDJSON token lines + a done trailer matching the
        # non-streamed completion for the same prompt; with logprobs
        # each line carries the token's raw-distribution logprob
        for with_lp in (False, True):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(
                    {"prompts": [[1, 2, 3]], "stream": True,
                     "logprobs": with_lp}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as r:
                assert r.headers["Content-Type"] == "application/x-ndjson"
                lines = [json.loads(l) for l in r.read().splitlines()]
            want = np.asarray(
                generate(
                    model, params, jnp.asarray([[1, 2, 3]], jnp.int32), 5
                )
            )[0].tolist()
            assert [l["token"] for l in lines[:-1]] == want
            assert lines[-1]["done"] and lines[-1]["completion"] == want
            if with_lp:
                assert all("logprob" in l for l in lines[:-1])
                assert lines[-1]["logprobs"] == [
                    l["logprob"] for l in lines[:-1]
                ]
            else:
                assert "logprobs" not in lines[-1]

        # per-token logprobs ride along when asked (engine mode)
        code, body = _post(
            port, "/generate",
            {"prompts": [[1, 2, 3]], "logprobs": True},
        )
        assert code == 200
        assert len(body["logprobs"]) == 1
        assert len(body["logprobs"][0]) == len(body["completions"][0])
        assert all(lp <= 0.0 for lp in body["logprobs"][0])

        # per-request decode budget (capped by the server's config)
        code, body = _post(
            port, "/generate",
            {"prompts": [[1, 2, 3]], "max_new_tokens": 2},
        )
        assert code == 200
        want = np.asarray(
            generate(model, params, jnp.asarray([[1, 2, 3]], jnp.int32), 2)
        )[0].tolist()
        assert body["completions"] == [want]
        code, body = _post(
            port, "/generate",
            {"prompts": [[1]], "max_new_tokens": 99},
        )
        assert code == 400 and "budget" in body["error"]

        # streaming guardrails: multi-prompt body is a 400, and an
        # over-width prompt 400s BEFORE the 200/NDJSON commits (the
        # engine validates at stream() call time, not first iteration)
        code, body = _post(
            port, "/generate",
            {"prompts": [[1], [2]], "stream": True},
        )
        assert code == 400 and "one prompt" in body["error"]
        # (chunked mode admits over-width prompts, so the eager-400
        # guardrail is the KV-capacity check here)
        code, body = _post(
            port, "/generate", {"prompts": [[1] * 127], "stream": True}
        )
        assert code == 400 and "max_seq_len" in body["error"]
    finally:
        server.shutdown()

    # fixed-path-only options are rejected at startup, not first request
    import pytest as _pytest

    for bad in (
        dict(batch_window=0.2),
        dict(draft_checkpoint=ckpt_dir),
    ):
        with _pytest.raises(ValueError, match="does not compose"):
            serve_model.make_server(None, port=0, gen={**gen, **bad})


def test_cli_score_mode(tmp_path):
    """--score emits per-token logprobs + totals matching a direct
    forward pass."""
    import jax.numpy as jnp

    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    seqs = [[1, 2, 3, 4], [7, 5, 6]]
    pfile = tmp_path / "seqs.jsonl"
    pfile.write_text(
        "".join(json.dumps({"tokens": s}) + "\n" for s in seqs)
    )
    ofile = tmp_path / "scores.jsonl"
    rc = main(
        [
            "--checkpoint", ckpt_dir,
            "--model", "tiny",
            "--config-overrides", '{"remat": false, "dtype": "float32"}',
            "--prompts", str(pfile),
            "--output", str(ofile),
            "--score",
            "--batch-size", "2",
        ]
    )
    assert rc == 0
    got = [json.loads(l) for l in ofile.read_text().splitlines()]
    assert len(got) == len(seqs)
    for row, seq in zip(got, seqs):
        logits = model.apply(
            {"params": params}, jnp.asarray([seq[:-1]], jnp.int32)
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        want = [
            float(logp[0, i, seq[i + 1]]) for i in range(len(seq) - 1)
        ]
        np.testing.assert_allclose(row["logprobs"], want, atol=1e-4)
        np.testing.assert_allclose(
            row["total"], sum(want), atol=1e-3
        )


def test_serve_model_score_endpoint(tmp_path):
    """/score returns per-token next-token logprobs matching a direct
    forward pass, in both fixed and continuous-engine modes."""
    import threading

    from tensorflowonspark_tpu.tools import serve_model

    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    seqs = [[1, 2, 3, 4], [7, 5, 6]]

    def ref_logprobs(seq):
        import jax.numpy as jnp

        logits = model.apply(
            {"params": params}, jnp.asarray([seq[:-1]], jnp.int32)
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return [
            float(logp[0, i, seq[i + 1]]) for i in range(len(seq) - 1)
        ]

    for engine_mode in (None, "continuous"):
        gen = dict(
            checkpoint=ckpt_dir,
            model="tiny",
            config_overrides='{"remat": false, "dtype": "float32"}',
            width=8,
            batch_size=2,
            max_new_tokens=4,
        )
        if engine_mode:
            gen["engine"] = engine_mode
        server = serve_model.make_server(None, port=0, gen=gen)
        port = server.server_address[1]
        threading.Thread(
            target=server.serve_forever, daemon=True
        ).start()
        try:
            code, body = _post(port, "/score", {"sequences": seqs})
            assert code == 200, body
            for got, seq in zip(body["logprobs"], seqs):
                want = ref_logprobs(seq)
                np.testing.assert_allclose(got, want, atol=1e-4)
            # validation: short row and over-long row are client faults
            code, body = _post(port, "/score", {"sequences": [[1]]})
            assert code == 400 and ">= 2 tokens" in body["error"]
            code, body = _post(
                port, "/score", {"sequences": [[1] * 99]}
            )
            assert code == 400 and "width" in body["error"]
            code, body = _post(
                port, "/score",
                {"sequences": [[1, cfg.vocab_size + 3]]},
            )
            assert code == 400 and "vocabulary" in body["error"]
        finally:
            server.shutdown()


def test_serve_model_generate_endpoint(tmp_path):
    """POST /generate against a live ephemeral-port server in
    --llama-checkpoint mode; completions match the CLI/library decode."""
    import threading

    from tensorflowonspark_tpu.tools import serve_model

    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    server = serve_model.make_server(
        None,
        port=0,
        gen=dict(
            checkpoint=ckpt_dir,
            model="tiny",
            config_overrides='{"remat": false, "dtype": "float32"}',
            width=8,
            batch_size=2,
            max_new_tokens=5,
        ),
    )
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        def post(path, payload):
            return _post(port, path, payload)

        code, body = post(
            "/generate", {"prompts": [[1, 2, 3], [4, 5, 6, 7, 8]]}
        )
        assert code == 200, body
        comps = body["completions"]
        assert len(comps) == 2 and all(len(c) == 5 for c in comps)

        # reference: library decode on the same padded batch
        padded = np.zeros((2, 8), np.int32)
        padded[0, :3] = [1, 2, 3]
        padded[1, :5] = [4, 5, 6, 7, 8]
        key = jax.random.split(jax.random.PRNGKey(0))[1]
        ref = np.asarray(
            generate(
                model, params, jnp.asarray(padded), max_new_tokens=5,
                rng=key, prompt_lengths=jnp.asarray([3, 5]),
            )
        )
        assert comps == ref.tolist()

        # errors are 400s, not hangs
        code, body = post("/generate", {"prompts": [[1] * 9]})
        assert code == 400 and "decode width" in body["error"]
        code, body = post("/predict", {"rows": [1]})
        assert code == 400
    finally:
        server.shutdown()


def test_serve_model_multi_lora_bank_checkpoint(tmp_path):
    """A saved multi-LoRA bank checkpoint serves per-request adapters
    end-to-end: orbax restores the bank as plain dicts (static scale
    and pytree classes are not stored), _load_params rewraps them, and
    the HTTP "adapter" field routes each request — matching generate()
    under that adapter's single-LoRA tree."""
    import threading

    import optax

    from tensorflowonspark_tpu.compute import TrainState
    from tensorflowonspark_tpu.compute.checkpoint import CheckpointManager
    from tensorflowonspark_tpu.ops import lora
    from tensorflowonspark_tpu.tools import serve_model

    cfg = LlamaConfig.tiny(dtype=jnp.float32, remat=False)
    model = Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    def trained(seed):
        tree = lora.add_lora(params, rank=4, rng=jax.random.PRNGKey(seed))
        keys = iter(jax.random.split(jax.random.PRNGKey(seed + 50), 200))
        return jax.tree.map(
            lambda x: lora.LoraTensor(
                base=x.base,
                a=x.a,
                b=0.02
                * jax.random.normal(next(keys), x.b.shape, x.b.dtype),
                scale=x.scale,
            )
            if isinstance(x, lora.LoraTensor)
            else x,
            tree,
            is_leaf=lambda x: isinstance(x, lora.LoraTensor),
        )

    bank = lora.multi_lora_bank([trained(1), trained(2)])
    ckpt_dir = str(tmp_path / "bank_ckpt")
    with CheckpointManager(ckpt_dir, async_save=False) as mgr:
        mgr.save(0, TrainState.create(bank, optax.sgd(0.1)), force=True)

    gen = dict(
        checkpoint=ckpt_dir,
        model="tiny",
        config_overrides='{"remat": false, "dtype": "float32"}',
        width=8,
        batch_size=2,
        max_new_tokens=5,
        engine="continuous",
    )
    server = serve_model.make_server(None, port=0, gen=gen)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        prompt = [5, 3, 1, 7]
        for k in range(3):
            want = np.asarray(
                generate(
                    model,
                    lora.select_adapter(bank, k),
                    jnp.asarray([prompt], jnp.int32),
                    5,
                )
            )[0].tolist()
            code, body = _post(
                port, "/generate",
                {"prompts": [prompt], "adapter": k},
            )
            assert code == 200, body
            assert body["completions"] == [want], k
        code, body = _post(
            port, "/generate", {"prompts": [[1, 2]], "adapter": 9}
        )
        assert code == 400 and "out of range" in body["error"]
    finally:
        server.shutdown()


def test_serve_model_n_samples(tmp_path):
    """The "n" field fans one prompt into n independently-sampled
    completions (regrouped per prompt); greedy n>1 and streaming n>1
    are rejected as meaningless."""
    import threading

    from tensorflowonspark_tpu.tools import serve_model

    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    gen = dict(
        checkpoint=ckpt_dir,
        model="tiny",
        config_overrides='{"remat": false, "dtype": "float32"}',
        width=8,
        batch_size=4,
        max_new_tokens=8,
        engine="continuous",
        seed=7,
    )
    server = serve_model.make_server(None, port=0, gen=gen)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        code, body = _post(
            port, "/generate",
            {"prompts": [[1, 2], [5, 6, 7]], "n": 3,
             "temperature": 0.9, "max_new_tokens": 6},
        )
        assert code == 200, body
        assert len(body["completions"]) == 2
        for group in body["completions"]:
            assert len(group) == 3
            assert all(len(c) == 6 for c in group)
        # sampled fan-out should produce some diversity across 3 draws
        assert any(
            len({tuple(c) for c in group}) > 1
            for group in body["completions"]
        )
        code, body = _post(
            port, "/generate", {"prompts": [[1, 2]], "n": 3}
        )
        assert code == 400 and "temperature" in body["error"]
        # negative temperature is greedy too (engine selects temps > 0)
        code, body = _post(
            port, "/generate",
            {"prompts": [[1, 2]], "n": 3, "temperature": -1},
        )
        assert code == 400 and "temperature" in body["error"]
        code, body = _post(
            port, "/generate",
            {"prompts": [[1, 2]], "n": 3, "temperature": 0.9,
             "stream": True},
        )
        assert code == 400 and "n must be 1" in body["error"]
        code, body = _post(
            port, "/generate",
            {"prompts": [[1, 2]], "n": 99, "temperature": 0.9},
        )
        assert code == 400 and "[1, 16]" in body["error"]
    finally:
        server.shutdown()

    # a server with a SAMPLED default temperature accepts n without a
    # per-request temperature (the guard checks the EFFECTIVE temp)
    server2 = serve_model.make_server(
        None, port=0, gen={**gen, "temperature": 0.8}
    )
    port2 = server2.server_address[1]
    threading.Thread(target=server2.serve_forever, daemon=True).start()
    try:
        code, body = _post(
            port2, "/generate",
            {"prompts": [[1, 2]], "n": 2, "max_new_tokens": 4},
        )
        assert code == 200, body
        assert len(body["completions"][0]) == 2
    finally:
        server2.shutdown()


def test_serve_model_openai_completions(tmp_path):
    """/v1/completions is an OpenAI-shaped alias over the continuous
    engine: token-id prompts in, text_completion envelope out (ids in
    choices[].tokens — no tokenizer in scope), with the OpenAI defaults
    (max_tokens 16, temperature 1.0) rather than the engine's, and
    clear 400s for the text-in/text-out fields this server cannot
    honor. GET /v1/models serves the SDK handshake."""
    import threading
    import urllib.request

    from tensorflowonspark_tpu.tools import serve_model

    cfg, model, params, ckpt_dir = _tiny_checkpoint(tmp_path)
    gen = dict(
        checkpoint=ckpt_dir,
        model="tiny",
        config_overrides='{"remat": false, "dtype": "float32"}',
        width=8,
        batch_size=4,
        max_new_tokens=8,
        engine="continuous",
        served_model_name="tiny-fp32",
    )
    server = serve_model.make_server(None, port=0, gen=gen)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v1/models"
        ) as r:
            models = json.loads(r.read())
        assert models["object"] == "list"
        assert models["data"][0]["id"] == "tiny-fp32"

        # greedy (temperature 0) matches the library decode exactly
        want = np.asarray(
            generate(model, params, jnp.asarray([[2, 4]], jnp.int32), 5)
        )[0].tolist()
        code, body = _post(
            port, "/v1/completions",
            {"prompt": [2, 4], "max_tokens": 5, "temperature": 0},
        )
        assert code == 200, body
        assert body["object"] == "text_completion"
        assert body["model"] == "tiny-fp32"
        assert body["id"].startswith("cmpl-")
        (choice,) = body["choices"]
        assert choice["tokens"] == want
        assert choice["text"] == ""  # token-id server
        assert choice["finish_reason"] == "length"
        assert body["usage"] == {
            "prompt_tokens": 2,
            "completion_tokens": 5,
            "total_tokens": 7,
        }

        # multiple prompts + n: flat choice order, prompt 0's samples
        # first; logprobs -> per-token sampled logprobs
        code, body = _post(
            port, "/v1/completions",
            {"prompt": [[1, 2], [5, 6, 7]], "n": 2, "max_tokens": 4,
             "temperature": 0.9, "seed": 11, "logprobs": 1},
        )
        assert code == 200, body
        assert [c["index"] for c in body["choices"]] == [0, 1, 2, 3]
        for c in body["choices"]:
            assert len(c["tokens"]) == 4
            lp = c["logprobs"]["token_logprobs"]
            assert len(lp) == 4 and all(v <= 0.0 for v in lp)
        assert body["usage"]["prompt_tokens"] == 5
        assert body["usage"]["completion_tokens"] == 16

        # seeded requests reproduce through the OpenAI surface too
        code2, body2 = _post(
            port, "/v1/completions",
            {"prompt": [[1, 2], [5, 6, 7]], "n": 2, "max_tokens": 4,
             "temperature": 0.9, "seed": 11, "logprobs": 1},
        )
        assert code2 == 200
        assert [c["tokens"] for c in body2["choices"]] == [
            c["tokens"] for c in body["choices"]
        ]

        # a hit stop sequence reports finish_reason "stop"
        code, body = _post(
            port, "/v1/completions",
            {"prompt": [2, 4], "max_tokens": 5, "temperature": 0,
             "stop": want[1:3]},
        )
        assert code == 200, body
        assert body["choices"][0]["tokens"] == want[:1]
        assert body["choices"][0]["finish_reason"] == "stop"

        # text-world fields are explained, not mis-served
        code, body = _post(
            port, "/v1/completions",
            {"prompt": "Once upon a time", "max_tokens": 4},
        )
        assert code == 400 and "tokenizer" in body["error"]
        code, body = _post(
            port, "/v1/completions",
            {"prompt": [2, 4], "stop": ["\n"]},
        )
        assert code == 400 and "tokenizer" in body["error"]
        code, body = _post(
            port, "/v1/completions",
            {"prompt": [2, 4], "echo": True},
        )
        assert code == 400 and "echo" in body["error"]
        code, body = _post(
            port, "/v1/completions",
            {"prompt": [2, 4], "stream": True},
        )
        assert code == 400 and "stream" in body["error"]
        # over-budget max_tokens rides the existing validation
        code, body = _post(
            port, "/v1/completions",
            {"prompt": [2, 4], "max_tokens": 999},
        )
        assert code == 400 and "max_new_tokens" in body["error"]
        # ...as does an explicit 0 (OpenAI allows it; we say why not)
        code, body = _post(
            port, "/v1/completions",
            {"prompt": [2, 4], "max_tokens": 0},
        )
        assert code == 400 and "max_new_tokens" in body["error"]
        # the all-defaults request must NOT 400 on a small-budget
        # server: the OpenAI default 16 clamps to the budget (8 here)
        code, body = _post(
            port, "/v1/completions",
            {"prompt": [2, 4], "temperature": 0},
        )
        assert code == 200, body
        assert len(body["choices"][0]["tokens"]) == 8
        # logprobs: 0 is valid OpenAI (sampled-token logprobs, no
        # top-alternatives) — not a falsy "omit"
        code, body = _post(
            port, "/v1/completions",
            {"prompt": [2, 4], "max_tokens": 3, "temperature": 0,
             "logprobs": 0},
        )
        assert code == 200, body
        assert len(body["choices"][0]["logprobs"]["token_logprobs"]) == 3
    finally:
        server.shutdown()
