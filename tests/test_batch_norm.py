"""FusedBatchNorm: value/grad parity with flax nn.BatchNorm.

The op exists for bandwidth (one variadic-reduce pass per direction —
see ops/batch_norm.py's profile rationale); these tests pin that the
fused pass structure did not change the math.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu.ops import bn_kernels
from tensorflowonspark_tpu.ops.batch_norm import (
    FusedBatchNorm,
    batch_norm_stats,
    fused_batch_norm,
)


def _ref_apply(x, gamma, beta, eps):
    mean = x.mean(axis=(0, 1, 2))
    var = x.var(axis=(0, 1, 2))
    return (x - mean) / np.sqrt(var + eps) * gamma + beta


def test_fused_batch_norm_matches_reference_fp32():
    rng = np.random.default_rng(0)
    x = rng.normal(2.0, 3.0, (4, 5, 6, 16)).astype(np.float32)
    gamma = rng.normal(1.0, 0.2, (16,)).astype(np.float32)
    beta = rng.normal(0.0, 0.2, (16,)).astype(np.float32)
    y = fused_batch_norm(jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta), 1e-5)
    np.testing.assert_allclose(y, _ref_apply(x, gamma, beta, 1e-5), atol=1e-4)


def test_fused_batch_norm_grads_match_autodiff_reference():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(0.5, 2.0, (3, 4, 4, 8)).astype(np.float32))
    gamma = jnp.asarray(rng.normal(1.0, 0.3, (8,)).astype(np.float32))
    beta = jnp.asarray(rng.normal(size=(8,)).astype(np.float32))
    t = jnp.asarray(rng.normal(size=x.shape).astype(np.float32))

    def fused_loss(x, g, b):
        return jnp.sum(fused_batch_norm(x, g, b, 1e-5) * t)

    def ref_loss(x, g, b):
        mean = x.mean(axis=(0, 1, 2))
        var = x.var(axis=(0, 1, 2))
        y = (x - mean) * jax.lax.rsqrt(var + 1e-5) * g + b
        return jnp.sum(y * t)

    gf = jax.grad(fused_loss, argnums=(0, 1, 2))(x, gamma, beta)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=2e-3, rtol=1e-4)


def test_batch_norm_stats_one_pass_matches_numpy():
    rng = np.random.default_rng(2)
    x = rng.normal(-1.0, 4.0, (2, 3, 3, 4)).astype(np.float32)
    mean, var = batch_norm_stats(jnp.asarray(x))
    np.testing.assert_allclose(mean, x.mean(axis=(0, 1, 2)), atol=1e-5)
    np.testing.assert_allclose(var, x.var(axis=(0, 1, 2)), atol=1e-4)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_module_parity_with_flax_batchnorm(dtype):
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(1.0, 2.0, (4, 6, 6, 12))).astype(dtype)

    fused = FusedBatchNorm(momentum=0.9, epsilon=1e-5, dtype=dtype)
    flaxbn = nn.BatchNorm(momentum=0.9, epsilon=1e-5, dtype=dtype)
    vf = fused.init(jax.random.key(0), x, use_running_average=False)
    vx = flaxbn.init(jax.random.key(0), x, use_running_average=False)

    yf, mf = fused.apply(
        vf, x, use_running_average=False, mutable=["batch_stats"]
    )
    yx, mx = flaxbn.apply(
        vx, x, use_running_average=False, mutable=["batch_stats"]
    )
    tol = 1e-4 if dtype == jnp.float32 else 6e-2
    np.testing.assert_allclose(
        np.asarray(yf, np.float32), np.asarray(yx, np.float32), atol=tol
    )
    # Running stats: same variable names and momentum convention.
    sf = mf["batch_stats"]
    sx = mx["batch_stats"]
    for k in ("mean", "var"):
        np.testing.assert_allclose(
            np.asarray(sf[k]), np.asarray(sx[k]), atol=tol
        )

    # Eval path uses the updated running stats identically.
    vf2 = {"params": vf["params"], "batch_stats": mf["batch_stats"]}
    vx2 = {"params": vx["params"], "batch_stats": mx["batch_stats"]}
    ye_f = fused.apply(vf2, x, use_running_average=True)
    ye_x = flaxbn.apply(vx2, x, use_running_average=True)
    np.testing.assert_allclose(
        np.asarray(ye_f, np.float32), np.asarray(ye_x, np.float32), atol=tol
    )


def test_grad_does_not_leak_through_running_stats():
    # The running-stat update must not contribute cotangents to params:
    # grads with the mutable stat update active must EQUAL grads from
    # the pure normalize (update disabled via init-mode apply).
    x = jnp.asarray(np.random.default_rng(4).normal(size=(2, 3, 3, 4)), jnp.float32)
    m = FusedBatchNorm()
    v = m.init(jax.random.key(0), x, use_running_average=False)

    def loss_with_update(params):
        y, _ = m.apply(
            {"params": params, "batch_stats": v["batch_stats"]},
            x,
            use_running_average=False,
            mutable=["batch_stats"],
        )
        return jnp.sum(y * y)

    def loss_pure(params):
        y = fused_batch_norm(
            x, params["scale"], params["bias"], m.epsilon
        )
        return jnp.sum(y * y)

    g_upd = jax.grad(loss_with_update)(v["params"])
    g_pure = jax.grad(loss_pure)(v["params"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        g_upd,
        g_pure,
    )


@pytest.fixture
def pallas_interpret(monkeypatch):
    """Run the Pallas stats kernels in the interpreter (CPU CI)."""
    monkeypatch.setattr(bn_kernels, "INTERPRET", True)


@pytest.mark.parametrize(
    "shape",
    [
        (7, 4),  # smaller than one block in both dims
        (1030, 65),  # partial final row block + sub-lane channel count
        (2050, 600),  # multiple column blocks, partial in both dims
    ],
)
def test_pair_stats_pallas_matches_numpy(pallas_interpret, shape):
    rng = np.random.default_rng(10)
    x = rng.normal(0.5, 2.0, shape).astype(np.float32)
    s, q = bn_kernels.pair_stats(jnp.asarray(x))
    assert s.dtype == jnp.float32 and q.dtype == jnp.float32
    np.testing.assert_allclose(s, x.sum(0), rtol=1e-5, atol=1e-3)
    np.testing.assert_allclose(q, (x * x).sum(0), rtol=1e-5, atol=1e-3)


def test_cross_stats_pallas_matches_numpy(pallas_interpret):
    rng = np.random.default_rng(11)
    dy = rng.normal(0.0, 1.0, (1030, 130)).astype(np.float32)
    x = rng.normal(1.0, 2.0, (1030, 130)).astype(np.float32)
    sdy, sdyx = bn_kernels.cross_stats(jnp.asarray(dy), jnp.asarray(x))
    np.testing.assert_allclose(sdy, dy.sum(0), rtol=1e-5, atol=1e-3)
    np.testing.assert_allclose(sdyx, (dy * x).sum(0), rtol=1e-5, atol=1e-3)


def test_pair_stats_pallas_bf16_stream_fp32_accumulate(pallas_interpret):
    rng = np.random.default_rng(12)
    x = rng.normal(2.0, 3.0, (520, 64)).astype(np.float32)
    xb = jnp.asarray(x).astype(jnp.bfloat16)
    s, q = bn_kernels.pair_stats(xb)
    ref_s = np.asarray(xb, np.float32).sum(0)
    ref_q = (np.asarray(xb, np.float32) ** 2).sum(0)
    np.testing.assert_allclose(s, ref_s, rtol=1e-4, atol=1e-2)
    np.testing.assert_allclose(q, ref_q, rtol=1e-4, atol=1e-1)


def test_fused_batch_norm_pallas_matches_xla_path(pallas_interpret):
    """Values AND the full custom-VJP gradient must agree between the
    Pallas-streamed stats path and the XLA reduce path (the backward
    derives sum(dy·x̂) from raw sums in the Pallas path — different
    rounding order, same math)."""
    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.normal(0.5, 2.0, (3, 5, 5, 24)).astype(np.float32))
    gamma = jnp.asarray(rng.normal(1.0, 0.3, (24,)).astype(np.float32))
    beta = jnp.asarray(rng.normal(size=(24,)).astype(np.float32))
    t = jnp.asarray(rng.normal(size=x.shape).astype(np.float32))

    def loss(impl, x, g, b):
        return jnp.sum(fused_batch_norm(x, g, b, 1e-5, impl=impl) * t)

    y_p = fused_batch_norm(x, gamma, beta, 1e-5, impl="pallas")
    y_x = fused_batch_norm(x, gamma, beta, 1e-5, impl="xla")
    np.testing.assert_allclose(np.asarray(y_p), np.asarray(y_x), atol=1e-5)

    g_p = jax.grad(lambda *a: loss("pallas", *a), argnums=(0, 1, 2))(x, gamma, beta)
    g_x = jax.grad(lambda *a: loss("xla", *a), argnums=(0, 1, 2))(x, gamma, beta)
    for a, b in zip(g_p, g_x):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-4)


def test_resnet_tiny_trains_through_pallas_bn(pallas_interpret, monkeypatch):
    """Full-model integration of the Pallas stats path: a tiny ResNet
    forward+backward with use_pallas forced on (interpreter kernels) —
    the program shape the single-chip ResNet bench compiles. Guards the
    jit+custom_vjp+kernel wiring inside a real conv net, not just the
    op-level tests above."""
    monkeypatch.setattr(bn_kernels, "use_pallas", lambda impl="auto": True)
    from tensorflowonspark_tpu.models.resnet import ResNet, ResNetConfig

    model = ResNet(ResNetConfig.tiny())
    x = jnp.asarray(
        np.random.default_rng(5).normal(size=(2, 32, 32, 3)), jnp.float32
    )
    variables = model.init(jax.random.PRNGKey(0), x, train=False)

    def loss(params):
        logits, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x,
            train=True,
            mutable=["batch_stats"],
        )
        return jnp.mean(logits**2)

    val, grads = jax.value_and_grad(loss)(variables["params"])
    assert np.isfinite(float(val))
    leaves = jax.tree.leaves(grads)
    assert leaves and all(np.all(np.isfinite(np.asarray(g))) for g in leaves)
    # The BN scale/bias gradients specifically must be nonzero — they
    # come straight out of the Pallas backward's (sum_dy, sum_dy_xhat),
    # so an all-zero kernel regression is visible HERE even while conv
    # gradients stay nonzero.
    bn_total = sum(
        float(np.abs(np.asarray(g)).sum())
        for path, g in jax.tree_util.tree_flatten_with_path(grads)[0]
        if "BatchNorm" in "/".join(str(k) for k in path)
    )
    assert bn_total > 0


def test_use_pallas_auto_always_resolves_to_xla(monkeypatch):
    """'auto' must resolve to the XLA reduces on every backend: the
    round-5 chip A/B measured the in-context Pallas stats path at 8.9%
    MFU on ResNet-50 vs 16.1% through XLA (the opaque pallas_call
    severs producer/consumer fusion around each BN layer — see
    BASELINE.md). Only an explicit impl='pallas' opts in."""
    monkeypatch.setattr(bn_kernels.jax, "default_backend", lambda: "tpu")
    assert bn_kernels.use_pallas("auto") is False
    assert bn_kernels.use_pallas("pallas") is True  # explicit overrides

    monkeypatch.setattr(bn_kernels.jax, "devices", lambda: [object()])
    assert bn_kernels.use_pallas("auto") is False  # even single-device TPU
    assert bn_kernels.use_pallas("xla") is False


def test_module_stats_computed_once_not_via_cse():
    """The module passes one set of stats to both the normalize and the
    running-average update; the HLO of a train-mode apply must contain
    exactly ONE forward stats reduction over the activation (two sums —
    sum and sum-of-squares — but of one streamed pass), not a second
    recompute for the running stats."""
    x = jnp.ones((4, 8, 8, 16), jnp.bfloat16)
    m = FusedBatchNorm(dtype=jnp.bfloat16, impl="xla")
    v = m.init(jax.random.key(0), x, use_running_average=False)

    def apply(vars_, x):
        y, upd = m.apply(vars_, x, use_running_average=False, mutable=["batch_stats"])
        return jnp.sum(y), upd

    text = jax.jit(apply).lower(v, x).as_text()
    # StableHLO: reductions print as 'stablehlo.reduce' over
    # 'tensor<4x8x8x16xf32>' operands. Sanity-check the predicate finds
    # SOMETHING (guards against dialect drift re-vacuating this test),
    # then bound the count: one streamed pass = one fused reduce region
    # with two init values (sum + sum-of-squares) — at most 2 reduce ops
    # mentioning the full activation, not 4 (a recompute for the
    # running-average update would double it).
    reduce_lines = [
        line
        for line in text.splitlines()
        if "stablehlo.reduce" in line
        and "tensor<4x8x8x16xf32>" in line
        # channel stats reduce over all-but-channel dims; the harness's
        # own jnp.sum(y) loss reduces over [0, 1, 2, 3] and must not count
        and "dimensions = [0, 1, 2]" in line
    ]
    assert reduce_lines, "predicate matched nothing - dialect drift?"
    assert len(reduce_lines) <= 2, "\n".join(reduce_lines)


def test_conv_nets_keep_batchnorm_checkpoint_names():
    """The FusedBatchNorm swap pins explicit name="BatchNorm_N" at every
    conv-net call site, so checkpoints saved in the nn.BatchNorm era (and
    nn.BatchNorm-based ports of the same architectures) restore without a
    tree rename — docs/SWITCHING.md "BatchNorm checkpoint compatibility"."""
    import jax
    from tensorflowonspark_tpu.models.inception import (
        InceptionConfig,
        InceptionV3,
    )
    from tensorflowonspark_tpu.models.resnet import ResNet, ResNetConfig
    from tensorflowonspark_tpu.models.vgg import VGG, VGGConfig

    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    for model in (
        ResNet(ResNetConfig.tiny()),
        InceptionV3(InceptionConfig.tiny()),
        VGG(VGGConfig.tiny()),
    ):
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        flat = jax.tree_util.tree_flatten_with_path(variables)[0]
        paths = {
            "/".join(str(k) for k in path) for path, _ in flat
        }
        assert not any("FusedBatchNorm" in p for p in paths), sorted(
            p for p in paths if "FusedBatchNorm" in p
        )[:3]
        assert any("BatchNorm_0" in p for p in paths), type(model).__name__


# ---------------------------------------------------------------------------
# Pod-safe Pallas BN: the shard_map route for multi-device TPU processes —
# per-shard Pallas partial sums + psum over the batch axes, gated on the
# ambient mesh the train/eval-step builders publish.
# ---------------------------------------------------------------------------


def _batch_mesh():
    from tensorflowonspark_tpu.compute.mesh import make_mesh

    return make_mesh({"data": 2, "fsdp": 4})


def test_stats_mesh_gate(monkeypatch):
    from tensorflowonspark_tpu.compute.mesh import make_mesh
    from tensorflowonspark_tpu.parallel import use_mesh

    monkeypatch.setattr(bn_kernels, "TREAT_AS_TPU", True)
    mesh = _batch_mesh()
    with use_mesh(mesh):
        # explicit 'pallas' takes the mesh route (a raw pallas_call on
        # GSPMD-sharded operands would be replicated); 'auto' and 'xla'
        # never touch the kernels since the round-5 regression measure
        assert bn_kernels.stats_mesh("pallas", 16) is mesh
        assert bn_kernels.stats_mesh("pallas", 9) is None  # indivisible
        assert bn_kernels.stats_mesh("auto", 16) is None
        assert bn_kernels.stats_mesh("xla", 16) is None
    assert bn_kernels.stats_mesh("pallas", 16) is None  # no ambient mesh
    with use_mesh(make_mesh({"data": 4, "model": 2})):
        # a model-sharded mesh means someone else owns the layout
        assert bn_kernels.stats_mesh("pallas", 16) is None
    monkeypatch.setattr(bn_kernels, "TREAT_AS_TPU", False)
    with use_mesh(mesh):
        assert bn_kernels.stats_mesh("pallas", 16) is None  # CPU backend


def test_mesh_stats_match_single_device(pallas_interpret):
    """Per-shard partial sums + psum must equal the single-device kernel
    (exact identities under the batch split; fp32 order differs)."""
    rng = np.random.default_rng(21)
    mesh = _batch_mesh()
    x = jnp.asarray(rng.normal(0.5, 2.0, (16, 5, 5, 48)).astype(np.float32))
    dy = jnp.asarray(rng.normal(size=(16, 5, 5, 48)).astype(np.float32))
    s_m, q_m = bn_kernels.mesh_pair_stats(x, mesh)
    s_1, q_1 = bn_kernels.pair_stats(x)
    np.testing.assert_allclose(np.asarray(s_m), np.asarray(s_1), rtol=1e-6, atol=1e-4)
    np.testing.assert_allclose(np.asarray(q_m), np.asarray(q_1), rtol=1e-6, atol=1e-4)
    sd_m, sx_m = bn_kernels.mesh_cross_stats(dy, x, mesh)
    sd_1, sx_1 = bn_kernels.cross_stats(dy, x)
    np.testing.assert_allclose(np.asarray(sd_m), np.asarray(sd_1), rtol=1e-6, atol=1e-4)
    np.testing.assert_allclose(np.asarray(sx_m), np.asarray(sx_1), rtol=1e-6, atol=1e-4)


def test_bn_train_mesh_route_matches_xla(pallas_interpret, monkeypatch):
    """Explicit 'pallas' on a multi-device 'TPU' with an ambient batch
    mesh resolves to the shard_map route (forward AND custom-VJP
    backward), with values and gradients matching the XLA reduce path."""
    from tensorflowonspark_tpu.parallel import use_mesh

    monkeypatch.setattr(bn_kernels, "TREAT_AS_TPU", True)
    pair_calls, cross_calls = [], []
    real_pair, real_cross = bn_kernels.mesh_pair_stats, bn_kernels.mesh_cross_stats
    monkeypatch.setattr(
        bn_kernels, "mesh_pair_stats",
        lambda *a: (pair_calls.append(1), real_pair(*a))[1],
    )
    monkeypatch.setattr(
        bn_kernels, "mesh_cross_stats",
        lambda *a: (cross_calls.append(1), real_cross(*a))[1],
    )
    rng = np.random.default_rng(22)
    x = jnp.asarray(rng.normal(0.5, 2.0, (16, 5, 5, 24)).astype(np.float32))
    gamma = jnp.asarray(rng.normal(1.0, 0.3, (24,)).astype(np.float32))
    beta = jnp.asarray(rng.normal(size=(24,)).astype(np.float32))
    t = jnp.asarray(rng.normal(size=x.shape).astype(np.float32))
    mesh = _batch_mesh()

    def loss(impl, x, g, b):
        return jnp.sum(fused_batch_norm(x, g, b, 1e-5, impl=impl) * t)

    with use_mesh(mesh):
        y_m = fused_batch_norm(x, gamma, beta, 1e-5, impl="pallas")
        g_m = jax.grad(lambda *a: loss("pallas", *a), argnums=(0, 1, 2))(
            x, gamma, beta
        )
    assert pair_calls, "forward did not take the mesh route"
    assert cross_calls, "backward did not take the mesh route"
    y_x = fused_batch_norm(x, gamma, beta, 1e-5, impl="xla")
    g_x = jax.grad(lambda *a: loss("xla", *a), argnums=(0, 1, 2))(
        x, gamma, beta
    )
    np.testing.assert_allclose(np.asarray(y_m), np.asarray(y_x), atol=1e-5)
    for a, b in zip(g_m, g_x):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-4
        )
