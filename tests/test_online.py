"""The online continual loop (ISSUE 19): chaos-survivable training on
live traffic.

Tier-1 scope (fast, in-process):

- the traffic log (``feed/livelog.py``): rotation seals columnar
  segments and atomically publishes manifests; ``append`` never raises
  and never blocks the serve path (drops are counted, by reason);
  torn-tail recovery truncates and seals instead of dying; the disk
  budget drops oldest sealed segments (counted) so a lagging trainer
  bounds disk, never grows it; a publication lost to the
  ``online.manifest_publish`` failpoint is republished by recovery;
- manifest discovery (``discover_manifests``): per-seq filtering,
  ordering, malformed-file tolerance;
- the growing-dataset wire: ``TFCluster.extend_shards`` appends under
  the SAME membership epoch with a bumped plan generation (``seq``),
  completion is gated on final cursors covering the newest generation,
  and a lingering ``IngestFeed`` adopts exactly the appended streams;
- the driver loop (``online.py``): discover→extend each step, per-seq
  dedup, stall onset/recovery (+ the ``online.train_stall`` and
  ``online.discover`` failpoints), the wire-decodable freshness
  beacon, and cycle outcomes.

Slow/e2e scope: a real elastic cluster consuming a dataset that GROWS
mid-run while a SIGKILL takes out a trainer node — the survivor
absorbs the orphaned shard, consumption over the grown dataset is
zero-gap with duplicates bounded by one publication interval, and the
chief's checkpoint publications keep advancing; and a live serving
fleet under load surviving a replica death (drain + respawn) and a
rollout killed mid-swap (rolled back, then retried to completion) with
zero dropped requests and zero dropped log records. (SIGKILL of a
subprocess serving replica is pinned by
``tests/test_fleet.py::test_fleet_sigkill_replica_under_streaming_load``;
here the same engine-death verdict is injected via
``fleet.report_failure`` so the loop-level assertions stay cheap.)
"""

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu.feed import livelog
from tensorflowonspark_tpu.feed.livelog import (
    TrafficLog,
    decode_records,
    discover_manifests,
    manifest_to_file,
)
from tensorflowonspark_tpu.feed.manifest import (
    FileManifest,
    read_manifest,
    stream_id,
)
from tensorflowonspark_tpu.utils import failpoints

MANIFEST_DIR = "manifests"


@pytest.fixture(autouse=True)
def _disarm():
    yield
    failpoints.disarm_all()


def _dropped(reason: str) -> float:
    return livelog.metrics()["dropped"].value(reason=reason)


def _fill(log: TrafficLog, n: int, base: int = 0, version="v0") -> None:
    for i in range(base, base + n):
        assert log.append(
            [i, i + 1], [i + 2], outcome=1.0,
            weights_version=version, trace_id=f"t{i}",
        )


# -- traffic log --------------------------------------------------------------


def test_trafficlog_rotation_seals_and_publishes(tmp_path):
    root = str(tmp_path / "log")
    log = TrafficLog(root, rotate_records=8, frame_records=4)
    _fill(log, 20)
    # 2 full segments sealed by rotation; 4 records still active
    ms = discover_manifests(root)
    assert [m["seq"] for m in ms] == [0, 1]
    assert all(m["records"] == 8 for m in ms)
    # the driver-facing flush hook seals the partial tail
    sealed = log.rotate()
    assert sealed is not None and sealed["records"] == 4
    ms = discover_manifests(root)
    assert [(m["seq"], m["records"]) for m in ms] == [(0, 8), (1, 8), (2, 4)]
    for m in ms:
        assert m["stream"] == "live"
        assert os.path.getsize(m["path"]) == m["bytes"]
        assert m["first_unix"] <= m["last_unix"] <= m["sealed_unix"]
    # round-trip through the ingest plane's reader: stamps and token
    # lengths survive the fixed-width columnar encoding
    rows = list(
        decode_records(read_manifest(manifest_to_file(ms[0])))
    )
    assert [r["trace_id"] for r in rows] == [f"t{i}" for i in range(8)]
    assert [r["prompt"].tolist() for r in rows[:2]] == [[0, 1], [1, 2]]
    assert all(r["weights_version"] == "v0" for r in rows)
    log.close()


def test_trafficlog_append_never_raises_and_counts_drops(tmp_path):
    log = TrafficLog(str(tmp_path / "log"), rotate_records=8)
    before = _dropped("failpoint")
    failpoints.arm("online.log_append", "drop", count=1)
    assert log.append([1], [2]) is False  # dropped, not raised
    assert _dropped("failpoint") == before + 1
    assert log.append([1], [2]) is True  # the next one lands
    before_closed = _dropped("closed")
    log.close()
    assert log.append([1], [2]) is False
    assert _dropped("closed") == before_closed + 1


def test_trafficlog_torn_tail_recovery(tmp_path):
    root = str(tmp_path / "log")
    log = TrafficLog(root, rotate_records=100, frame_records=2)
    _fill(log, 6)  # 3 flushed frames in the active segment
    active = [f for f in os.listdir(root) if f.endswith(".active")]
    assert len(active) == 1
    path = os.path.join(root, active[0])
    # the crash: the process dies mid-append, tearing the tail frame
    with open(path, "ab") as f:
        f.write(b"TFC\x01" + b"\x99" * 37)
    del log  # no close(): the writer is gone
    # recovery runs at construction: the torn tail is truncated, the
    # surviving records sealed + published
    log2 = TrafficLog(root, rotate_records=100, frame_records=2)
    ms = discover_manifests(root)
    assert len(ms) == 1 and ms[0]["records"] == 6
    rows = list(decode_records(read_manifest(manifest_to_file(ms[0]))))
    assert [r["trace_id"] for r in rows] == [f"t{i}" for i in range(6)]
    # the writer resumes on a fresh seq after the recovered one
    _fill(log2, 2, base=6)
    assert log2.rotate()["seq"] > ms[0]["seq"]
    log2.close()


def test_trafficlog_disk_budget_drops_oldest_counted(tmp_path):
    root = str(tmp_path / "log")
    log = TrafficLog(root, rotate_records=4, frame_records=4)
    _fill(log, 8)  # 2 sealed segments, no budget pressure yet
    before = _dropped("disk_budget")
    assert len(discover_manifests(root)) == 2
    log.disk_budget_bytes = 1  # force: every seal now evicts the rest
    _fill(log, 4)
    ms = discover_manifests(root)
    # drop-oldest keeps the newest segment only; evicted segment files
    # AND manifests are gone; every lost record is counted
    assert len(ms) == 1 and ms[0]["seq"] == 2
    assert _dropped("disk_budget") == before + 8
    assert sorted(f for f in os.listdir(root) if f.endswith(".tfc")) == [
        os.path.basename(ms[0]["path"])
    ]
    log.close()


def test_manifest_publish_failpoint_republished_on_recover(tmp_path):
    root = str(tmp_path / "log")
    log = TrafficLog(root, rotate_records=100)
    _fill(log, 3)
    failpoints.arm("online.manifest_publish", "drop", count=1)
    # the segment seals (the .tfc lands on disk) but the publication
    # is LOST, so rotate() has no manifest to hand back
    assert log.rotate() is None
    assert [f for f in os.listdir(root) if f.endswith(".tfc")]
    assert discover_manifests(root) == []
    log.close(seal=False)
    # construction-time recovery notices the sealed-but-unpublished
    # segment and republishes its manifest
    log2 = TrafficLog(root, rotate_records=100)
    ms = discover_manifests(root)
    assert len(ms) == 1 and ms[0]["records"] == 3
    log2.close()


def test_discover_manifests_filters_and_skips_malformed(tmp_path):
    root = str(tmp_path / "log")
    log = TrafficLog(root, rotate_records=2, frame_records=2)
    _fill(log, 6)  # 3 sealed segments
    mdir = os.path.join(root, MANIFEST_DIR)
    with open(os.path.join(mdir, "garbage.json"), "w") as f:
        f.write("{not json")
    ms = discover_manifests(root, after_seq=0)
    assert [m["seq"] for m in ms] == [1, 2]
    assert discover_manifests(root, stream="other") == []
    failpoints.arm("online.discover", "raise", count=1)
    with pytest.raises(failpoints.FailpointError):
        discover_manifests(root)
    log.close()


# -- the driver loop ----------------------------------------------------------


class _StubCluster:
    def __init__(self):
        self.extended: list = []
        self.holds: list = []

    def extend_shards(self, files):
        self.extended.append(list(files))

    def hold_ingest_completion(self, hold=True):
        self.holds.append(hold)


def test_online_loop_discovers_extends_and_dedups(tmp_path):
    from tensorflowonspark_tpu.cluster import wire
    from tensorflowonspark_tpu.online import OnlineLoop

    root = str(tmp_path / "log")
    log = TrafficLog(root, rotate_records=4, frame_records=4)
    c = _StubCluster()
    versions = ["v0"]
    loop = OnlineLoop(
        c, root, progress_fn=lambda: versions[-1], stall_after_s=60.0
    )
    assert loop.step()["outcome"] == "idle"
    _fill(log, 4)
    s = loop.step()
    assert s["outcome"] == "ok" and s["discovered"] == 1
    assert len(c.extended) == 1
    assert c.extended[0][0].format == "columnar"
    # already-extended segments never re-extend
    assert loop.step()["outcome"] == "idle"
    assert loop.stats()["records_extended"] == 4
    # the beacon is a wire-decodable pointer record
    with open(os.path.join(root, "freshness.json")) as f:
        doc = wire.decode("online.freshness", json.load(f))
    assert doc["cycle"] == 3 and doc["trained_records"] == 4
    log.close()


def test_online_loop_stall_onset_recovery_and_failpoints(tmp_path):
    from tensorflowonspark_tpu.online import OnlineLoop, metrics

    root = str(tmp_path / "log")
    log = TrafficLog(root, rotate_records=2, frame_records=2)
    c = _StubCluster()
    versions = ["v0"]
    loop = OnlineLoop(
        c, root, progress_fn=lambda: versions[-1], stall_after_s=2.0
    )
    t0 = time.time()
    _fill(log, 2)
    assert loop.step(now=t0)["outcome"] == "ok"  # progress token seen
    _fill(log, 2, base=2)
    assert loop.step(now=t0 + 1.0)["outcome"] == "ok"
    # fresh data keeps arriving but the trainer stops moving: one
    # stall ONSET (counted once), not one per poll
    before = metrics()["cycles"].value(outcome="stall")
    s = loop.step(now=t0 + 4.0)
    assert s["outcome"] == "stall" and s["loop_lag_s"] > 2.0
    assert loop.step(now=t0 + 5.0)["outcome"] == "idle"
    assert metrics()["cycles"].value(outcome="stall") == before + 1
    assert loop.stats()["stalls"] == 1 and loop.stats()["stalled"]
    # progress resumes: the stall clears
    versions.append("v1")
    loop.step(now=t0 + 6.0)
    assert not loop.stats()["stalled"]
    # chaos knobs: a discover failure is an outcome, not a crash; a
    # train_stall drop hides one poll's progress
    failpoints.arm("online.discover", "raise", count=1)
    assert loop.step()["outcome"] == "discover_error"
    failpoints.arm("online.train_stall", "drop", count=1)
    versions.append("v2")
    assert loop.step()["weights_version"] == "v1"
    assert loop.step()["weights_version"] == "v2"
    log.close()


def test_online_loop_start_stop_holds_and_releases_completion(tmp_path):
    from tensorflowonspark_tpu.online import OnlineLoop

    c = _StubCluster()
    loop = OnlineLoop(
        c, str(tmp_path), progress_fn=lambda: "v0",
        poll_interval_s=0.02,
    )
    loop.start()
    time.sleep(0.15)
    loop.stop()
    assert c.holds == [True, False]
    assert loop.stats()["cycles"] >= 2


# -- the growing-dataset wire (driver side) -----------------------------------


def _colf(tmp_path, n, name):
    from tensorflowonspark_tpu.feed import columnar as col

    p = str(tmp_path / name)
    col.write_frames(
        p, [{"x": np.float32(i)} for i in range(n)], records_per_frame=5
    )
    return FileManifest(p, format="columnar")


def test_extend_shards_appends_and_bumps_seq(tmp_path, monkeypatch):
    from tests.test_handover import _capture_publishes, _standin_cluster

    m0 = _colf(tmp_path, 10, "a.colf")
    m1 = _colf(tmp_path, 10, "b.colf")
    m2 = _colf(tmp_path, 10, "c.colf")
    c = _standin_cluster([0], {0: [m0]}, {}, epoch=0)
    published = _capture_publishes(monkeypatch)
    c.extend_shards([m1])
    plan = published[0]
    assert plan["seq"] == 1 and plan["epoch"] == 0
    assert [m.path for m in plan["manifests"]] == [m0.path, m1.path]
    # a second growth bumps the generation again, same epoch
    c.extend_shards([m2])
    assert published[0]["seq"] == 2
    assert len(published[0]["manifests"]) == 3


def test_extend_shards_requires_handover(tmp_path):
    from tests.test_handover import _standin_cluster

    m = _colf(tmp_path, 5, "a.colf")
    c = _standin_cluster([0], {0: []}, {}, handover=False)
    with pytest.raises(RuntimeError, match="handover"):
        c.extend_shards([m])


def test_completion_gated_on_plan_seq_and_hold(tmp_path, monkeypatch):
    """All-finals at the current epoch does NOT complete the plan when
    (a) a final predates the newest growth generation, or (b) the
    online hold is set — only a release plus seq-covering finals do."""
    from tests.test_handover import _capture_publishes, _standin_cluster

    m0 = _colf(tmp_path, 10, "a.colf")
    m1 = _colf(tmp_path, 10, "b.colf")
    cursors = {
        0: {
            "epoch": 1,
            "final": True,
            "plan_seq": 0,
            "cursor": {stream_id(m0): 1},
        }
    }
    c = _standin_cluster([0], {0: [m0]}, cursors, epoch=1)
    _capture_publishes(monkeypatch)
    c._ingest_seq = 1  # growth happened after that final was published
    c._maybe_complete_ingest()
    assert not c._ingest_complete  # stale-generation final ignored
    cursors[0]["plan_seq"] = 1  # the final now covers the growth
    c.hold_ingest_completion(True)
    c._maybe_complete_ingest()
    assert not c._ingest_complete  # the online loop holds it open
    c.hold_ingest_completion(False)
    c._maybe_complete_ingest()
    assert c._ingest_complete
    c.extend_shards([m1])  # growth un-latches a completed dataset
    assert not c._ingest_complete


def test_linger_adopts_growth_seq_bump(tmp_path):
    """Consumer side of the wire: a lingering feed (shard exhausted,
    FINAL cursor published) adopts a SAME-epoch plan whose ``seq``
    bumped — consuming exactly the appended streams, then lingers
    again until the driver's completion marker, and its finals are
    stamped with the generation they cover."""
    from tensorflowonspark_tpu.feed.ingest import IngestFeed

    m0 = _colf(tmp_path, 15, "a.colf")
    m1 = _colf(tmp_path, 10, "b.colf")
    state = {
        "epoch": 0, "seq": 1, "manifests": [m0], "complete": False,
    }
    published: list[dict] = []

    def plan_fetch(min_epoch, timeout):
        return {
            "epoch": state["epoch"],
            "seq": state["seq"],
            "manifests": list(state["manifests"]),
            "handover": True,
            "complete": state["complete"],
        }

    feed = IngestFeed(
        [m0],
        input_mapping={"x": "x"},
        plan_seq=1,
        plan_fetch=plan_fetch,
        cursor_publish=published.append,
        epoch_watch=lambda: state["epoch"],
    )
    out: list = []
    done = threading.Event()

    def consume():
        out.extend(feed.batch_stream(5))
        done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    deadline = time.monotonic() + 20
    while not any(
        p.get("final") and p.get("plan_seq") == 1 for p in published
    ):
        assert time.monotonic() < deadline, published
        time.sleep(0.05)
    assert not done.is_set()  # lingering, not complete
    # the growth: same epoch, bumped generation, appended manifest
    state["manifests"] = [m0, m1]
    state["seq"] = 2
    deadline = time.monotonic() + 20
    while not any(
        p.get("final") and p.get("plan_seq") == 2 for p in published
    ):
        assert time.monotonic() < deadline, published
        time.sleep(0.05)
    assert not done.is_set()  # adopted + consumed, lingering again
    state["complete"] = True
    assert done.wait(20)
    vals = sorted(
        float(v) for b in out for v in np.ravel(b["x"])
    )
    # every record of the GROWN dataset exactly once
    assert vals == sorted(
        [float(i) for i in range(15)] + [float(i) for i in range(10)]
    )
    assert len(vals) == 25  # zero duplicates, zero gaps
    assert feed.plan_seq == 2


# -- chaos e2e ----------------------------------------------------------------


def _read_traces(tmp_path, eid):
    with open(tmp_path / f"consumed{eid}.json") as f:
        return json.load(f)


@pytest.mark.slow
@pytest.mark.e2e
def test_online_chaos_sigkill_trainer_exactly_once(tmp_path):
    """Chaos acceptance (ISSUE 19), trainer plane: live traffic keeps
    sealing while the dataset grows mid-run and a SIGKILL takes out a
    trainer node with NO replacement — the survivor absorbs the
    orphaned shard (elastic reshard), the loop keeps extending, the
    chief's checkpoint publications keep advancing, and consumption
    over the WHOLE grown dataset is zero-gap with duplicates bounded
    by one cursor-publication interval."""
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.serving.rollout import read_latest
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    from tests import cluster_fns
    from tests.test_chaos import _node_pid

    frame_records = 5
    publish_blocks = 2
    batch = 5
    root = str(tmp_path / "traffic")
    channel = str(tmp_path / "channel")
    log = TrafficLog(
        root, rotate_records=20, frame_records=frame_records
    )
    written: list[str] = []

    def write(n):
        base = len(written)
        for i in range(base, base + n):
            assert log.append(
                [i % 97], [i % 89], outcome=1.0,
                weights_version="v0", trace_id=f"t{i}",
            )
            written.append(f"t{i}")
        log.rotate()

    write(40)  # the seed dataset
    args = {
        "dir": str(tmp_path),
        "batch": batch,
        "publish_blocks": publish_blocks,
        "step_sleep": 0.2,
        "ckpt_batches": 3,
        "channel": channel,
    }
    cluster = tfcluster.run(
        cluster_fns.online_consumer_fn,
        args,
        num_executors=2,
        input_mode=InputMode.TENSORFLOW,
        elastic=True,
        reservation_timeout=120,
        heartbeat_interval=0.5,
        heartbeat_grace=3.0,
        handover_timeout=20.0,
        env=cpu_only_env(),
        flightrec_dir=str(tmp_path / "logs"),
    )
    sup_err: list[BaseException] = []

    def supervise():
        try:
            cluster.supervise(poll=0.5)
        except BaseException as e:  # noqa: BLE001 - asserted below
            sup_err.append(e)

    sup = threading.Thread(target=supervise, daemon=True)
    loop = None
    try:
        seed = discover_manifests(root)
        cluster.assign_shards([manifest_to_file(m) for m in seed])
        sup.start()
        loop = cluster.run_online(
            root,
            channel_dir=channel,
            after={m["stream"]: m["seq"] for m in seed},
            poll_interval_s=0.3,
            stall_after_s=120.0,
        )
        # the dataset grows while both nodes train
        write(20)
        pid = _node_pid(cluster, 1)
        deadline = time.monotonic() + 60
        while True:
            assert time.monotonic() < deadline, "node 1 never consumed"
            assert not sup_err, sup_err
            try:
                if len(_read_traces(tmp_path, 1)["traces"]) >= 10:
                    break
            except (OSError, json.JSONDecodeError):
                pass
            time.sleep(0.1)
        os.kill(pid, signal.SIGKILL)
        # growth AFTER the kill: the reshard and the growing dataset
        # compose — the survivor adopts both
        deadline = time.monotonic() + 60
        while cluster.membership_epoch() < 1:
            assert time.monotonic() < deadline, "no reshard"
            assert not sup_err, sup_err
            time.sleep(0.2)
        write(20)
        # everything written is eventually discovered and extended
        deadline = time.monotonic() + 90
        while loop.stats()["records_extended"] < len(written) - 40:
            assert time.monotonic() < deadline, loop.stats()
            assert not sup_err, sup_err
            time.sleep(0.2)
        loop.stop()  # releases the completion hold: the run may drain
        sup.join(timeout=240)
        assert not sup.is_alive(), "supervise never returned"
        assert not sup_err, sup_err
        cluster.shutdown(timeout=120)
    finally:
        if loop is not None:
            loop.stop()
        cluster.launcher.terminate()
        cluster.server.stop()
        log.close(seal=False)

    s0 = _read_traces(tmp_path, 0)
    s1 = _read_traces(tmp_path, 1)
    traces = s0["traces"] + s1["traces"]
    # zero-gap over the GROWN dataset: every written record consumed
    assert set(traces) == set(written)
    # duplicates bounded by one publication interval + in-flight batch
    dup = len(traces) - len(set(traces))
    assert dup <= publish_blocks * frame_records + batch, dup
    # the survivor adopted the crash reshard
    assert max(s0["epochs"]) >= 1
    assert os.path.exists(tmp_path / "done0")
    # trainer progress was really published and really observed; the
    # drain keeps publishing after stop(), so take one explicit step
    # to observe the terminal version
    loop.step()
    latest = read_latest(channel)
    assert latest is not None and latest.version.startswith("step-")
    assert loop.stats()["weights_version"] == latest.version
    assert loop.stats()["stalls"] == 0
    fr = json.load(open(tmp_path / "logs" / "flightrec-driver.json"))
    kinds = [e.get("kind") for e in fr["events"]]
    assert "online_cycle" in kinds
    assert "ingest_plan_republish" in kinds


@pytest.mark.slow
def test_online_serving_chaos_replica_death_and_midswap_rollback(tmp_path):
    """Chaos acceptance (ISSUE 19), serving plane: a 2-replica fleet
    under streaming load feeds the traffic log while versions roll
    mid-run — one replica dies (engine-death verdict → drain +
    respawn) and one rollout is killed mid-swap (rolled back, the
    retry completes). Zero hard request errors, zero hung workers,
    zero dropped log records; the tail serves the final live-trained
    version."""
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models.llama import Llama, LlamaConfig
    from tensorflowonspark_tpu.online import OnlineLoop
    from tensorflowonspark_tpu.serving import ContinuousBatcher
    from tensorflowonspark_tpu.serving.fleet import READY, ServingFleet
    from tensorflowonspark_tpu.serving.rollout import RolloutController
    from tensorflowonspark_tpu.serving.router import FleetRouter

    cfg = LlamaConfig.tiny(dtype=jnp.float32, remat=False)
    model = Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    def factory():
        return ContinuousBatcher(model, params, slots=2, prompt_widths=(8,))

    fleet = ServingFleet(
        factory=factory,
        replicas=2,
        probe_interval=0.5,
        warmup=False,
        drain_timeout=10.0,
        respawn_backoff_s=0.05,
    )
    router = FleetRouter(fleet)
    ctl = RolloutController(fleet, drain_timeout=20.0, verify_timeout=30.0)
    root = str(tmp_path / "traffic")
    log = TrafficLog(root, rotate_records=16, frame_records=8)
    dropped_before = sum(
        livelog.metrics()["dropped"].value(reason=r)
        for r in ("failpoint", "io_error", "closed", "disk_budget")
    )
    progress = {"v": "v0"}
    loop = OnlineLoop(
        _StubCluster(), root,
        progress_fn=lambda: progress["v"], stall_after_s=120.0,
    )
    results: dict[int, tuple] = {}
    stop = threading.Event()
    phase = {"current": "v0"}

    def load(widx):
        n = 0
        while not stop.is_set():
            key, n = widx * 10_000 + n, n + 1
            try:
                s = router.stream([1 + widx, 2, 3], 8, deadline_s=60.0)
                toks = list(s)
                results[key] = ("ok", s.weights_version, phase["current"])
                log.append(
                    [1 + widx, 2, 3], toks,
                    weights_version=s.weights_version,
                    trace_id=f"r{key}",
                )
            except BaseException as e:  # noqa: BLE001 - the verdict
                results[key] = ("err", type(e).__name__, phase["current"])
            time.sleep(0.02)

    def mkparams(seed):
        return jax.tree.map(
            np.asarray,
            model.init(
                jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32)
            )["params"],
        )

    threads = [
        threading.Thread(target=load, args=(i,), daemon=True)
        for i in range(2)
    ]
    try:
        list(router.stream([1, 2, 3], 8))  # pay the compile up front
        for t in threads:
            t.start()
        time.sleep(1.0)
        log.rotate()
        assert loop.step()["discovered"] >= 1
        # cycle 1: a clean in-loop rollout
        assert ctl.publish(mkparams(1), version="live1") == "completed"
        progress["v"] = phase["current"] = "live1"
        # chaos 1: a replica dies under load (the verdict a SIGKILLed
        # subprocess replica produces); the fleet drains + respawns
        victim = next(
            v["rid"] for v in fleet.views() if v["state"] == READY
        )
        gen = next(
            v["generation"] for v in fleet.views() if v["rid"] == victim
        )
        fleet.report_failure(victim, "chaos: engine died", generation=gen)
        deadline = time.monotonic() + 30
        while fleet.states()[victim] != READY:
            assert time.monotonic() < deadline, fleet.states()
            time.sleep(0.1)
        time.sleep(0.5)
        log.rotate()
        loop.step()
        # chaos 2: the next rollout dies mid-swap — rolled back, and
        # the serving set stays coherent; the retry completes
        failpoints.arm("rollout.swap", "raise", count=1)
        assert ctl.publish(mkparams(2), version="live2") == "rolled_back"
        assert ctl.publish(mkparams(2), version="live2") == "completed"
        progress["v"] = phase["current"] = "live2"
        time.sleep(1.0)  # the tail: live2 serves
        log.rotate()
        final = loop.step()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=120)
        router.close()
        log.close()
    hung = [t for t in threads if t.is_alive()]
    oks = [r for r in results.values() if r[0] == "ok"]
    errs = [r for r in results.values() if r[0] == "err"]
    sheds = [
        r for r in errs if r[1] in ("FleetOverloaded", "FleetUnavailable")
    ]
    # zero dropped requests: every request resolved ok or a typed shed
    assert not hung
    assert len(errs) == len(sheds), errs
    # zero dropped log records: the serve path's writes all landed
    # (delta: the dropped counter is process-global across tests)
    assert sum(
        livelog.metrics()["dropped"].value(reason=r)
        for r in ("failpoint", "io_error", "closed", "disk_budget")
    ) == dropped_before
    # the tail serves the final live-trained version
    tail = [r for r in oks if r[2] == "live2"]
    assert tail and all(r[1] == "live2" for r in tail)
    # the loop kept extending through both chaos events, no stalls
    assert loop.stats()["records_extended"] >= len(oks) - 16
    assert loop.stats()["stalls"] == 0
    assert final["outcome"] in ("ok", "idle")
