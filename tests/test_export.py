"""AOT export artifact tests (L7 parity: the Scala inference API's role —
self-describing exported model, batch inference with no user code).
Reference: src/main/scala/com/yahoo/tensorflowonspark/TFModel.scala (SURVEY §2.2).
"""

import json

import numpy as np
import pytest

from tensorflowonspark_tpu.api import export as aot_export

W = np.array([[2.0], [1.0]], np.float32)
B = 0.5


def _linear_state():
    import jax.numpy as jnp

    return {"w": jnp.asarray(W), "b": jnp.asarray([B])}


def _apply_array(state, batch):
    """batch: (n, 2) array -> (n, 1)."""
    return batch @ state["w"] + state["b"]


def _apply_dict(state, batch):
    """batch: {'x0': (n,), 'x1': (n,)} -> {'y': (n,)}."""
    x = batch["x0"] * state["w"][0, 0] + batch["x1"] * state["w"][1, 0]
    return {"y": x + state["b"][0]}


@pytest.fixture(scope="module")
def array_artifact(tmp_path_factory):
    d = tmp_path_factory.mktemp("aot") / "array_model"
    aot_export.export_model(
        _apply_array, _linear_state(), np.zeros((4, 2), np.float32), str(d)
    )
    return str(d)


@pytest.fixture(scope="module")
def dict_artifact(tmp_path_factory):
    d = tmp_path_factory.mktemp("aot") / "dict_model"
    example = {
        "x0": np.zeros((4,), np.float32),
        "x1": np.zeros((4,), np.float32),
    }
    aot_export.export_model(
        _apply_dict,
        _linear_state(),
        example,
        str(d),
        input_mapping={"x0": "x0", "x1": "x1"},
        output_mapping={"y": "pred"},
    )
    return str(d)


def test_export_round_trip_poly_batch(array_artifact):
    model = aot_export.load_model(array_artifact)
    # batch-polymorphic: sizes the exporter never saw
    for n in (1, 3, 7):
        x = np.arange(2 * n, dtype=np.float32).reshape(n, 2)
        np.testing.assert_allclose(
            np.asarray(model(x)), x @ W + B, rtol=1e-6
        )


def test_aot_transform_bare_rows(array_artifact):
    model = aot_export.load_model(array_artifact)
    rows = [(1.0, 2.0), (3.0, 4.0), (5.0, 6.0)]
    out = model.transform(rows, batch_size=2)
    got = [float(np.asarray(r).reshape(())) for r in out]
    np.testing.assert_allclose(got, [4.5, 10.5, 16.5], rtol=1e-6)


def test_aot_transform_column_mappings(dict_artifact):
    """Mappings travel inside the artifact: dict rows in, named cols out."""
    model = aot_export.load_model(dict_artifact)
    rows = [{"x0": 1.0, "x1": 2.0}, {"x0": 3.0, "x1": 4.0}]
    out = model.transform(rows, batch_size=8)
    assert [set(r) for r in out] == [{"pred"}, {"pred"}]
    np.testing.assert_allclose(
        [float(r["pred"]) for r in out], [4.5, 10.5], rtol=1e-6
    )


def test_tfmodel_loads_aot_artifact(array_artifact):
    """TFModel without export_fn falls back to the self-describing artifact."""
    from tensorflowonspark_tpu.api.pipeline import TFModel

    model = TFModel(export_dir=array_artifact, batch_size=2)
    out = model.transform([(1.0, 0.0), (0.0, 1.0)])
    got = [float(np.asarray(r).reshape(())) for r in out]
    np.testing.assert_allclose(got, [2.5, 1.5], rtol=1e-6)


def test_tfmodel_without_export_fn_or_artifact(tmp_path):
    from tensorflowonspark_tpu.api.pipeline import TFModel

    model = TFModel(export_dir=str(tmp_path))
    with pytest.raises(ValueError, match="export_fn"):
        model.transform([(1.0, 2.0)])


def test_run_model_cli_jsonl(array_artifact, tmp_path):
    from tensorflowonspark_tpu.tools import run_model

    inp = tmp_path / "in.jsonl"
    with open(inp, "w") as f:
        for row in [[1.0, 2.0], [3.0, 4.0]]:
            f.write(json.dumps(row) + "\n")
    out = tmp_path / "out.jsonl"
    rc = run_model.main(
        [
            "--export-dir", array_artifact,
            "--input", str(inp),
            "--output", str(out),
            "--format", "jsonl",
            "--batch-size", "2",
        ]
    )
    assert rc == 0
    rows = [json.loads(line) for line in open(out)]
    np.testing.assert_allclose(
        np.asarray(rows, np.float32).reshape(-1), [4.5, 10.5], rtol=1e-6
    )


def test_run_model_cli_tfrecord(dict_artifact, tmp_path):
    pytest.importorskip("tensorflow")
    from tensorflowonspark_tpu.data import dfutil
    from tensorflowonspark_tpu.tools import run_model

    in_dir = tmp_path / "records"
    dfutil.saveAsTFRecords(
        [{"x0": np.float32(1.0), "x1": np.float32(2.0)},
         {"x0": np.float32(3.0), "x1": np.float32(4.0)}],
        str(in_dir),
    )
    out_dir = tmp_path / "preds"
    rc = run_model.main(
        [
            "--export-dir", dict_artifact,
            "--input", str(in_dir),
            "--output", str(out_dir),
            "--format", "tfrecord",
        ]
    )
    assert rc == 0
    rows = list(dfutil.loadTFRecords(str(out_dir)))
    got = sorted(float(np.asarray(r["pred"]).reshape(())) for r in rows)
    np.testing.assert_allclose(got, [4.5, 10.5], rtol=1e-6)


def test_serve_model_http(dict_artifact):
    """The HTTP serving entry: health, signature, predictions, and error
    paths against a live (ephemeral-port) server."""
    import threading
    import urllib.error
    import urllib.request

    from tensorflowonspark_tpu.tools import serve_model

    server = serve_model.make_server(dict_artifact, port=0, batch_size=8)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{port}"
    try:
        health = json.load(urllib.request.urlopen(f"{base}/healthz"))
        assert health["status"] == "ok"
        sig = json.load(urllib.request.urlopen(f"{base}/signature"))
        assert sig["input_mapping"] == {"x0": "x0", "x1": "x1"}

        rows = [{"x0": 1.0, "x1": 2.0}, {"x0": 0.0, "x1": 0.0}]
        req = urllib.request.Request(
            f"{base}/predict",
            data=json.dumps({"rows": rows}).encode(),
            headers={"Content-Type": "application/json"},
        )
        out = json.load(urllib.request.urlopen(req))
        preds = out["predictions"]
        # y = 2*x0 + 1*x1 + 0.5, surfaced under the output_mapping name
        assert preds[0]["pred"] == pytest.approx(4.5)
        assert preds[1]["pred"] == pytest.approx(0.5)

        bad = urllib.request.Request(
            f"{base}/predict", data=json.dumps({"rows": []}).encode()
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(bad)
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{base}/nope")
        assert e.value.code == 404
    finally:
        server.shutdown()
        t.join(timeout=10)
