"""LoRA adapters (ops/lora.py): exact no-op at init, frozen base under
training, adapter-only optimizer state, merge equivalence, and the
sharded/decode paths."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflowonspark_tpu.models.llama import (
    Llama,
    LlamaConfig,
    llama_loss_fn,
    llama_param_shardings,
)
from tensorflowonspark_tpu.ops import lora
from tensorflowonspark_tpu.ops.lora import (
    LoraTensor,
    add_lora,
    lora_optimizer,
    merge_lora,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, remat=False)
    model = Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, 16), jnp.int32)
    )["params"]
    return cfg, model, params


def test_add_lora_is_exact_noop_at_init(tiny):
    cfg, model, params = tiny
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size
    ).astype(jnp.int32)
    base_logits = model.apply({"params": params}, tokens)
    lora_params = add_lora(params, rank=4, rng=jax.random.PRNGKey(2))
    lora_logits = model.apply({"params": lora_params}, tokens)
    # b is zero-init, so the adapter contributes exactly nothing
    np.testing.assert_array_equal(
        np.asarray(base_logits), np.asarray(lora_logits)
    )
    wrapped = [
        x for x in jax.tree.leaves(
            lora_params, is_leaf=lambda x: isinstance(x, LoraTensor)
        )
        if isinstance(x, LoraTensor)
    ]
    # 7 targets per layer x 2 layers in tiny()
    assert len(wrapped) == 14


def test_lora_training_freezes_base_and_learns(tiny):
    cfg, model, params = tiny
    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch

    mesh = make_mesh({"data": -1})
    # fresh buffers: the step donates its input state, and the module-
    # scoped fixture's arrays must survive for the other tests
    lora_params = add_lora(
        jax.tree.map(jnp.array, params), rank=4, rng=jax.random.PRNGKey(3)
    )
    tx = lora_optimizer(optax.adamw(1e-2), lora_params)
    state = TrainState.create(lora_params, tx)

    # optimizer moments exist ONLY for adapters: full adamw would carry
    # 2x params worth of moments; masked carries 2x adapter elements
    n_params = sum(x.size for x in jax.tree.leaves(lora_params))
    n_adapters = sum(
        x.a.size + x.b.size
        for x in jax.tree.leaves(
            lora_params, is_leaf=lambda x: isinstance(x, LoraTensor)
        )
        if isinstance(x, LoraTensor)
    )
    n_opt = sum(
        np.size(x) for x in jax.tree.leaves(state.opt_state)
    )
    assert n_opt < 2 * n_adapters + 64, (
        f"optimizer state has {n_opt} elements; expected ~2x adapters "
        f"({2 * n_adapters}), params are {n_params}"
    )

    def bases(tree):
        return [
            np.asarray(x.base)
            for x in jax.tree.leaves(
                tree, is_leaf=lambda x: isinstance(x, LoraTensor)
            )
            if isinstance(x, LoraTensor)
        ]

    # host copies BEFORE training: the train step donates its input
    # state, so the original device buffers are gone after step 1
    bases_before = bases(lora_params)

    token_loss = llama_loss_fn(model)
    loss_fn = lambda p, b: token_loss(p, b["tokens"])  # noqa: E731
    step = build_train_step(loss_fn, tx, mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (8, 17), 0, cfg.vocab_size
    ).astype(jnp.int32)
    batch = shard_batch(mesh, {"tokens": tokens})
    losses = []
    for _ in range(5):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses

    for before, after in zip(bases_before, bases(state.params)):
        np.testing.assert_array_equal(before, after)  # frozen, bit-exact
    trained_b = [
        np.abs(np.asarray(x.b)).max()
        for x in jax.tree.leaves(
            state.params, is_leaf=lambda x: isinstance(x, LoraTensor)
        )
        if isinstance(x, LoraTensor)
    ]
    assert max(trained_b) > 0  # adapters actually moved


def test_merge_lora_matches_adapter_forward(tiny):
    cfg, model, params = tiny
    lora_params = add_lora(params, rank=4, rng=jax.random.PRNGKey(5))
    # give the adapters nonzero weights so the merge is non-trivial
    lora_params = jax.tree.map(
        lambda x: (
            LoraTensor(
                base=x.base,
                a=x.a,
                b=jnp.ones_like(x.b) * 0.01,
                scale=x.scale,
            )
            if isinstance(x, LoraTensor)
            else x
        ),
        lora_params,
        is_leaf=lambda x: isinstance(x, LoraTensor),
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(6), (2, 10), 0, cfg.vocab_size
    ).astype(jnp.int32)
    with_adapters = model.apply({"params": lora_params}, tokens)
    merged = merge_lora(lora_params)
    assert not any(
        isinstance(x, LoraTensor)
        for x in jax.tree.leaves(
            merged, is_leaf=lambda x: isinstance(x, LoraTensor)
        )
    )
    merged_logits = model.apply({"params": merged}, tokens)
    np.testing.assert_allclose(
        np.asarray(with_adapters), np.asarray(merged_logits),
        rtol=1e-5, atol=1e-5,
    )


def test_lora_shardings_and_decode(tiny):
    """LoRA trees ride the mesh (base like its kernel, factors along
    their matching halves) and the KV-cache decode path."""
    from tensorflowonspark_tpu.compute.mesh import make_mesh
    from tensorflowonspark_tpu.models.llama import generate

    cfg, model, params = tiny
    lora_params = add_lora(params, rank=2, rng=jax.random.PRNGKey(7))
    mesh = make_mesh({"fsdp": 4, "model": 2})
    sh = llama_param_shardings(lora_params, mesh)
    placed = jax.device_put(lora_params, sh)

    def spec_of(tree, pred):
        from jax.sharding import PartitionSpec as P

        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            if pred("/".join(str(p) for p in path)):
                return leaf.spec
        raise AssertionError("leaf not found")

    from jax.sharding import PartitionSpec as P

    assert spec_of(sh, lambda s: "q_proj" in s and s.endswith(".base")) == P(
        "fsdp", "model"
    )
    assert spec_of(sh, lambda s: "q_proj" in s and s.endswith(".a")) == P(
        "fsdp", None
    )
    assert spec_of(sh, lambda s: "q_proj" in s and s.endswith(".b")) == P(
        None, "model"
    )
    assert spec_of(sh, lambda s: "o_proj" in s and s.endswith(".a")) == P(
        "model", None
    )

    prompt = jax.random.randint(
        jax.random.PRNGKey(8), (2, 6), 0, cfg.vocab_size
    ).astype(jnp.int32)
    plain = generate(model, params, prompt, max_new_tokens=5)
    lora_out = generate(model, jax.device_get(placed), prompt,
                        max_new_tokens=5)
    # zero-init adapters: decode identical to the base model
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(lora_out))


def test_lora_checkpoint_roundtrip(tiny, tmp_path):
    """LoRA train state rides orbax unchanged: LoraTensor nodes (and the
    masked optimizer state) save and restore bit-exactly — the
    llama_fsdp --lora-rank --model-dir resume path."""
    from tensorflowonspark_tpu.compute import TrainState
    from tensorflowonspark_tpu.compute.checkpoint import (
        CheckpointManager,
        restore_latest,
    )

    _, _, params = tiny
    lp = add_lora(params, rank=2, rng=jax.random.PRNGKey(9))
    tx = lora_optimizer(optax.adamw(1e-3), lp)
    state = TrainState.create(lp, tx)
    with CheckpointManager(str(tmp_path / "ck"), async_save=False) as mgr:
        mgr.save(1, state, force=True)
        mgr.wait()
        step, restored = restore_latest(mgr, state)
    assert step == 1
    for o, b in zip(
        jax.tree.leaves(state.params),
        jax.tree.leaves(restored.params),
        strict=True,
    ):
        np.testing.assert_array_equal(np.asarray(o), np.asarray(b))
    # the masked optimizer state (adapter-only moments) must roundtrip
    # too — a resume with re-initialized moments would ship green
    # without this
    for o, b in zip(
        jax.tree.leaves(state.opt_state),
        jax.tree.leaves(restored.opt_state),
        strict=True,
    ):
        np.testing.assert_array_equal(np.asarray(o), np.asarray(b))
    n_lora = sum(
        isinstance(x, LoraTensor)
        for x in jax.tree.leaves(
            restored.params, is_leaf=lambda x: isinstance(x, LoraTensor)
        )
    )
    assert n_lora == 14


def test_add_lora_validations(tiny):
    _, _, params = tiny
    with pytest.raises(ValueError, match="rank"):
        add_lora(params, rank=0, rng=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="no 2-D params"):
        add_lora(params, rank=2, rng=jax.random.PRNGKey(0),
                 targets=("nonexistent",))


# -- multi-LoRA serving (MultiLoraTensor bank + per-row routing) -------


def _trained_adapter(params, seed):
    """add_lora + fake-trained factors (b is zero-init, which would make
    every adapter a no-op and the routing test vacuous)."""
    import jax

    from tensorflowonspark_tpu.ops.lora import LoraTensor

    tree = lora.add_lora(params, rank=4, rng=jax.random.PRNGKey(seed))
    keys = iter(
        jax.random.split(jax.random.PRNGKey(seed + 100), 200)
    )

    def bump(x):
        if isinstance(x, LoraTensor):
            return LoraTensor(
                base=x.base,
                a=x.a,
                b=0.02 * jax.random.normal(next(keys), x.b.shape, x.b.dtype),
                scale=x.scale,
            )
        return x

    return jax.tree.map(
        bump, tree, is_leaf=lambda x: isinstance(x, LoraTensor)
    )


@pytest.fixture(scope="module")
def tiny_bank():
    import jax

    from tensorflowonspark_tpu.models.llama import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(dtype=jnp.float32, remat=False)
    model = Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    bank = lora.multi_lora_bank(
        [_trained_adapter(params, 1), _trained_adapter(params, 2)]
    )
    return cfg, model, params, bank


def test_multi_lora_bank_structure_and_selection(tiny_bank):
    import jax

    from tensorflowonspark_tpu.models.llama import generate

    cfg, model, params, bank = tiny_bank
    assert lora.bank_size(bank) == 3  # zero adapter + 2 trained
    toks = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    # slot 0 is the exact base model
    base = np.asarray(generate(model, params, toks, 4))
    sel0 = np.asarray(
        generate(model, lora.select_adapter(bank, 0), toks, 4)
    )
    np.testing.assert_array_equal(base, sel0)
    # trained slots actually change the model (the routing test below
    # would be vacuous otherwise)
    sel1 = model.apply({"params": lora.select_adapter(bank, 1)}, toks)
    np.testing.assert_raises(
        AssertionError, np.testing.assert_allclose,
        np.asarray(model.apply({"params": params}, toks)),
        np.asarray(sel1), 1e-4,
    )


def test_multi_lora_rows_route_independently(tiny_bank):
    """One forward with mixed adapter_ids must equal per-adapter
    single-LoraTensor forwards row by row."""
    cfg, model, params, bank = tiny_bank
    toks = jnp.asarray(
        [[1, 2, 3, 4], [1, 2, 3, 4], [1, 2, 3, 4]], jnp.int32
    )
    ids = jnp.asarray([0, 1, 2], jnp.int32)
    routed = np.asarray(
        model.apply({"params": bank}, toks, adapter_ids=ids)
    )
    for k in range(3):
        want = np.asarray(
            model.apply(
                {"params": lora.select_adapter(bank, k)}, toks[k : k + 1]
            )
        )[0]
        np.testing.assert_allclose(routed[k], want, atol=2e-5), k


def test_engine_multi_lora_per_request_adapters(tiny_bank):
    """Concurrent requests with different adapters share the engine's
    slots; each must match generate() under ITS adapter's single-LoRA
    tree. Prefix entries must not leak across adapters: the same prompt
    under another adapter misses and recomputes."""
    import threading

    from tensorflowonspark_tpu.models.llama import generate
    from tensorflowonspark_tpu.serving import ContinuousBatcher

    cfg, model, params, bank = tiny_bank
    eng = ContinuousBatcher(
        model, bank, slots=3, prompt_widths=(8,), prefill_chunk=3,
        prefix_cache=8,
    )
    try:
        assert eng.stats()["adapters"] == 3
        prompt = [5, 3, 1, 7]
        refs = {
            k: np.asarray(
                generate(
                    model,
                    lora.select_adapter(bank, k),
                    jnp.asarray([prompt], jnp.int32),
                    5,
                )
            )[0].tolist()
            for k in range(3)
        }
        assert refs[1] != refs[0] or refs[2] != refs[0]  # adapters bite
        results = {}

        def fire(k):
            results[k] = eng.submit(prompt, 5, adapter=k)

        threads = [
            threading.Thread(target=fire, args=(k,)) for k in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()
        assert results == refs
        # same prompt, same adapter -> prefix hit; the other adapters'
        # identical-token entries were not eligible
        hits0 = eng.stats()["prefix_hits"]
        assert eng.submit(prompt, 5, adapter=1) == refs[1]
        assert eng.stats()["prefix_hits"] == hits0 + 1
        # default adapter (None) == slot 0 == base
        assert eng.submit(prompt, 5) == refs[0]
        # validation: out-of-range adapter
        with pytest.raises(ValueError, match="out of range"):
            eng.submit(prompt, 2, adapter=7)
    finally:
        eng.close()


def test_engine_adapter_rejected_without_bank():
    import jax

    from tensorflowonspark_tpu.models.llama import Llama, LlamaConfig
    from tensorflowonspark_tpu.serving import ContinuousBatcher

    cfg = LlamaConfig.tiny(dtype=jnp.float32, remat=False)
    model = Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    eng = ContinuousBatcher(model, params, slots=1, prompt_widths=(8,))
    try:
        with pytest.raises(ValueError, match="no MultiLoraTensor bank"):
            eng.submit([1, 2], 2, adapter=1)
        assert "adapters" not in eng.stats()
    finally:
        eng.close()


def test_engine_multi_lora_tp_mesh_token_identical(tiny_bank):
    """Adapter routing composes with TP serving: bank factors replicate
    across the 'model' axis (every chip serves every adapter) while
    bases stay TP-sharded; tokens must match the unsharded engine per
    adapter."""
    from tensorflowonspark_tpu.compute.mesh import make_mesh
    from tensorflowonspark_tpu.serving import ContinuousBatcher

    cfg, model, params, bank = tiny_bank
    mesh = make_mesh({"data": 4, "model": 2})
    plain = ContinuousBatcher(model, bank, slots=2, prompt_widths=(8,))
    tp = ContinuousBatcher(
        model, bank, slots=2, prompt_widths=(8,), mesh=mesh
    )
    try:
        for k in range(3):
            p = [2, 4, 6]
            assert tp.submit(p, 4, adapter=k) == plain.submit(
                p, 4, adapter=k
            ), k
    finally:
        plain.close()
        tp.close()


def test_multi_lora_bank_rejects_mismatched_bases(tiny_bank):
    import jax

    cfg, model, params, bank = tiny_bank
    other = jax.tree.map(lambda x: x + 0.1, params)
    with pytest.raises(ValueError, match="different base"):
        lora.multi_lora_bank(
            [_trained_adapter(params, 1), _trained_adapter(other, 2)]
        )


def test_load_params_rewraps_lora_with_scale(tmp_path, tiny_bank):
    """Checkpoint round-trip of an alpha != rank adapter: orbax drops
    the static scale, and _load_params(lora_scale=...) re-applies it —
    restored outputs must match the original tree's."""
    import optax

    from tensorflowonspark_tpu.compute import TrainState
    from tensorflowonspark_tpu.compute.checkpoint import CheckpointManager
    from tensorflowonspark_tpu.tools.generate_text import _load_params

    cfg, model, params, _ = tiny_bank
    tree = lora.add_lora(
        params, rank=4, rng=jax.random.PRNGKey(3), alpha=8.0
    )  # scale 2.0
    keys = iter(jax.random.split(jax.random.PRNGKey(9), 200))
    tree = jax.tree.map(
        lambda x: lora.LoraTensor(
            base=x.base, a=x.a,
            b=0.02 * jax.random.normal(next(keys), x.b.shape, x.b.dtype),
            scale=x.scale,
        )
        if isinstance(x, lora.LoraTensor)
        else x,
        tree,
        is_leaf=lambda x: isinstance(x, lora.LoraTensor),
    )
    ckpt = str(tmp_path / "scaled_lora")
    with CheckpointManager(ckpt, async_save=False) as mgr:
        mgr.save(0, TrainState.create(tree, optax.sgd(0.1)), force=True)
    toks = jnp.asarray([[2, 7, 1, 8]], jnp.int32)
    want = np.asarray(model.apply({"params": tree}, toks))
    restored = _load_params(ckpt, cfg, lora_scale=2.0)
    got = np.asarray(model.apply({"params": restored}, toks))
    np.testing.assert_allclose(got, want, atol=2e-5)
    # and the default-scale restore is measurably different (the bug
    # the flag exists for)
    wrong = np.asarray(
        model.apply({"params": _load_params(ckpt, cfg)}, toks)
    )
    assert np.abs(wrong - want).max() > 1e-3
