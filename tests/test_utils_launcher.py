"""Coverage for the small parity modules: compat, device_info, util path
resolution, and the tpu-submit CLI front door."""

import os

import numpy as np
import pytest

from tensorflowonspark_tpu.utils import compat, device_info, util


def test_compat_export_and_noop_shims(tmp_path):
    path = compat.export_saved_model({"w": np.float32(2.0)}, str(tmp_path / "m"))
    from tensorflowonspark_tpu.compute.checkpoint import restore_checkpoint

    state = restore_checkpoint(path)
    assert float(np.asarray(state["w"])) == 2.0
    assert compat.disable_auto_shard() is None
    assert compat.disable_auto_shard(object()) is None  # accepts tf options
    assert isinstance(compat.is_gpu_available(), bool)


def test_device_info_shims():
    csv = device_info.get_gpus(num_gpu=2)
    assert csv == "0,1"  # conftest: 8 virtual CPU devices
    assert len(device_info.get_local_devices()) == 8
    assert device_info.is_tpu_available() is False  # CPU test mesh


def test_resolve_path_matrix(tmp_path):
    # scheme-qualified passes through
    assert util.resolve_path("hdfs://nn/a") == "hdfs://nn/a"
    # absolute + scheme default_fs -> prefixed
    assert (
        util.resolve_path("/data", default_fs="hdfs://nn") == "hdfs://nn/data"
    )
    # absolute + no scheme fs -> untouched
    assert util.resolve_path("/data", default_fs="") == "/data"
    # relative resolves against working dir, or cwd when unset
    assert (
        util.resolve_path("logs", working_dir=str(tmp_path))
        == f"{tmp_path}/logs"
    )
    assert util.resolve_path("logs") == f"{os.getcwd()}/logs"


def test_executor_id_pinning(tmp_path):
    assert util.read_executor_id(str(tmp_path)) is None
    util.write_executor_id(3, str(tmp_path))
    assert util.read_executor_id(str(tmp_path)) == 3


def test_launcher_main_runs_script_with_env(tmp_path, monkeypatch):
    """tpu-submit parses flags, exports TFOS_TPU_*/--conf env, runs the
    script as __main__ with its own argv."""
    from tensorflowonspark_tpu import launcher

    out = tmp_path / "out.txt"
    script = tmp_path / "driver.py"
    script.write_text(
        "import os, sys, json\n"
        "from tensorflowonspark_tpu.launcher import cluster_args_from_env\n"
        "payload = {'argv': sys.argv[1:],\n"
        "           'num': cluster_args_from_env()['num_executors'],\n"
        "           'conf': os.environ.get('MY_CONF')}\n"
        f"open({str(out)!r}, 'w').write(json.dumps(payload))\n"
    )
    monkeypatch.setattr("sys.argv", ["tpu-submit"])
    rc = launcher.main(
        [
            "--num-executors", "3",
            "--conf", "MY_CONF=hello",
            str(script),
            "--user-flag", "7",
        ]
    )
    assert rc == 0
    import json

    payload = json.loads(out.read_text())
    assert payload == {
        "argv": ["--user-flag", "7"],
        "num": 3,
        "conf": "hello",
    }


def test_launcher_rejects_bad_conf(tmp_path):
    from tensorflowonspark_tpu import launcher

    script = tmp_path / "s.py"
    script.write_text("pass\n")
    with pytest.raises(SystemExit):
        launcher.main(["--conf", "novalue", str(script)])


def test_export_tf_saved_model_roundtrip(tmp_path):
    """jax2tf SavedModel export loads and serves in TF (TF-serving interop,
    the artifact family the reference's Scala API consumed)."""
    tf = pytest.importorskip("tensorflow")
    import jax.numpy as jnp

    from tensorflowonspark_tpu.api.export import export_tf_saved_model

    state = {"w": jnp.asarray([[2.0], [1.0]]), "b": jnp.asarray([0.5])}

    def apply_fn(s, batch):
        return batch @ s["w"] + s["b"]

    d = str(tmp_path / "saved_model")
    export_tf_saved_model(apply_fn, state, np.zeros((4, 2), np.float32), d)
    loaded = tf.saved_model.load(d)
    for n in (2, 5):  # polymorphic batch dim
        x = np.arange(2 * n, dtype=np.float32).reshape(n, 2)
        got = np.asarray(loaded.f(tf.constant(x)))
        np.testing.assert_allclose(
            got, x @ np.array([[2.0], [1.0]], np.float32) + 0.5, rtol=1e-6
        )
