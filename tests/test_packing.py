"""Sequence packing: row assembly, padding, and training equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu.data.packing import pack_batches, pack_sequences


def _docs(lengths, vocab=100, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, vocab, size=n).astype(np.int32).tolist()
        for n in lengths
    ]


def test_rows_reconstruct_documents():
    docs = _docs([5, 7, 3, 9, 2])
    rows = list(pack_sequences(docs, seq_len=12))  # row_len 13
    # every token of every document appears exactly once, in order,
    # under a per-row-unique nonzero segment id; padding is (0, pad_id)
    recovered = []
    for row in rows:
        toks, segs = row["tokens"], row["segment_ids"]
        assert toks.shape == segs.shape == (13,)
        for sid in sorted(set(segs.tolist()) - {0}):
            recovered.append(toks[segs == sid].tolist())
        assert (toks[segs == 0] == 0).all()  # padding tokens are pad_id
    # split-continuations concatenate back in order
    flat = [t for doc in recovered for t in doc]
    assert flat == [t for doc in docs for t in doc]


def test_overlong_document_splits_or_drops():
    docs = _docs([30, 4])
    rows = list(pack_sequences(docs, seq_len=12))
    flat = [
        t
        for row in rows
        for t in row["tokens"][row["segment_ids"] != 0].tolist()
    ]
    assert flat == [t for doc in docs for t in doc]

    dropped = list(pack_sequences(docs, seq_len=12, drop_overlong=True))
    flat = [
        t
        for row in dropped
        for t in row["tokens"][row["segment_ids"] != 0].tolist()
    ]
    assert flat == docs[1]


def test_pack_batches_shapes_and_remainder():
    docs = _docs([6] * 10)
    batches = list(pack_batches(docs, batch_size=2, seq_len=12))
    for b in batches:
        assert b["tokens"].shape == (2, 13)
        assert b["segment_ids"].shape == (2, 13)
    kept = list(
        pack_batches(docs, batch_size=2, seq_len=12, drop_remainder=False)
    )
    assert len(kept) >= len(batches)


def test_packed_padded_row_trains_like_separate_docs():
    """The full contract: a packed row WITH tail padding gives exactly
    the per-document losses recombined by target count — padding (seg 0)
    contributes nothing."""
    from tensorflowonspark_tpu.models.llama import (
        Llama,
        LlamaConfig,
        llama_loss_fn,
    )

    cfg = LlamaConfig.tiny(dtype=jnp.float32, remat=False)
    model = Llama(cfg)
    rng = np.random.default_rng(3)
    a = rng.integers(1, cfg.vocab_size, size=7).astype(np.int32)
    b = rng.integers(1, cfg.vocab_size, size=5).astype(np.int32)

    (row,) = pack_sequences([a.tolist(), b.tolist()], seq_len=16)
    assert (row["segment_ids"][-5:] == 0).all()  # 12 tokens + 5 pad
    tokens = jnp.asarray(row["tokens"][None])
    seg = jnp.asarray(row["segment_ids"][None])

    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]
    loss = llama_loss_fn(model)
    packed = float(loss(params, tokens, segment_ids=seg))
    la = float(loss(params, jnp.asarray(a[None])))
    lb = float(loss(params, jnp.asarray(b[None])))
    np.testing.assert_allclose(packed, (la * 6 + lb * 4) / 10, rtol=1e-5)


def test_seq_len_validation():
    with pytest.raises(ValueError, match="seq_len"):
        list(pack_sequences([[1, 2]], seq_len=0))
