"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax loads.

This is the rebuild's version of the reference's local-mode Spark trick
(SURVEY.md §4): the whole distributed surface — mesh, shardings, the
control/data planes — is exercised on one box with no TPU pod.
"""

import os

# Must happen before any `import jax` anywhere in the test process, and
# before any node subprocess is spawned (children inherit this environ at
# exec, which is when sitecustomize TPU hooks would otherwise dial the
# accelerator — see utils.util.cpu_only_env).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["PALLAS_AXON_REMOTE_COMPILE"] = ""
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# sitecustomize-style TPU hooks may have imported jax at interpreter boot,
# BEFORE this file ran — in that case the env vars above were snapshotted
# too late and jax would still dial the TPU plugin at first backend init.
# jax_platforms is config-updatable any time before backends initialize,
# and XLA_FLAGS is read at CPU-client creation, so this pins tests to the
# 8-device virtual CPU mesh either way.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is compile-dominated (hundreds
# of jit programs, most identical across runs), so cache XLA executables
# on disk keyed by HLO hash. First run pays full compile; repeat runs —
# the local iteration loop this exists for — skip it. Safe across code
# changes (key = hash of the lowered program, not the Python source).
# Subprocess nodes inherit the env var and share the cache.
# Per-user path: a fixed /tmp name would break (or be poisonable) for
# every user but the first on a shared machine.
_cache_dir = os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "tensorflowonspark_tpu",
        "jax_test_compile_cache",
    ),
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402

# tfsan witness lifecycle (no-op unless TFOS_TFSAN=1): thin delegating
# hooks, because pytest honors `pytest_plugins` only in the rootdir
# conftest and this one lives under tests/.
from tests.plugins import tfsan as _tfsan_plugin  # noqa: E402


def pytest_configure(config):
    _tfsan_plugin.configure(config)


def pytest_sessionfinish(session, exitstatus):
    _tfsan_plugin.sessionfinish(session, exitstatus)


@pytest.fixture(scope="session")
def mesh8():
    from tensorflowonspark_tpu.compute.mesh import make_mesh

    return make_mesh({"data": 2, "fsdp": 4})


@pytest.fixture(scope="session")
def mesh_dp():
    from tensorflowonspark_tpu.compute.mesh import make_mesh

    return make_mesh({"data": 8})
