"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax loads.

This is the rebuild's version of the reference's local-mode Spark trick
(SURVEY.md §4): the whole distributed surface — mesh, shardings, the
control/data planes — is exercised on one box with no TPU pod.
"""

import os

# Must happen before any `import jax` anywhere in the test process, and
# before any node subprocess is spawned (children inherit this environ at
# exec, which is when sitecustomize TPU hooks would otherwise dial the
# accelerator — see utils.util.cpu_only_env).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["PALLAS_AXON_REMOTE_COMPILE"] = ""
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# sitecustomize-style TPU hooks may have imported jax at interpreter boot,
# BEFORE this file ran — in that case the env vars above were snapshotted
# too late and jax would still dial the TPU plugin at first backend init.
# jax_platforms is config-updatable any time before backends initialize,
# and XLA_FLAGS is read at CPU-client creation, so this pins tests to the
# 8-device virtual CPU mesh either way.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from tensorflowonspark_tpu.compute.mesh import make_mesh

    return make_mesh({"data": 2, "fsdp": 4})


@pytest.fixture(scope="session")
def mesh_dp():
    from tensorflowonspark_tpu.compute.mesh import make_mesh

    return make_mesh({"data": 8})
