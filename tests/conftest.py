"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax loads.

This is the rebuild's version of the reference's local-mode Spark trick
(SURVEY.md §4): the whole distributed surface — mesh, shardings, the
control/data planes — is exercised on one box with no TPU pod.
"""

import os

# Must happen before any `import jax` anywhere in the test process, and
# before any node subprocess is spawned (children inherit this environ at
# exec, which is when sitecustomize TPU hooks would otherwise dial the
# accelerator — see utils.util.cpu_only_env).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["PALLAS_AXON_REMOTE_COMPILE"] = ""
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# sitecustomize-style TPU hooks may have imported jax at interpreter boot,
# BEFORE this file ran — in that case the env vars above were snapshotted
# too late and jax would still dial the TPU plugin at first backend init.
# jax_platforms is config-updatable any time before backends initialize,
# and XLA_FLAGS is read at CPU-client creation, so this pins tests to the
# 8-device virtual CPU mesh either way.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: DISABLED (environment drift, found in
# PR 15's tier-1): on this jaxlib (0.4.36 CPU), a MULTI-DEVICE/sharded
# executable restored from the persistent cache corrupts the heap when
# executed more than once — glibc aborts with "corrupted double-linked
# list" (reproduced standalone: the sharded llama train step on the
# 8-device mesh passes on the compile run, SIGABRTs on every
# cache-hit run; single-device programs are unaffected;
# jax_persistent_cache_enable_xla_caches="none" does not help). The
# crash surfaced as native aborts in test_models /
# test_engine_pipeline and SIGSEGVs in bench subprocesses that
# inherited JAX_COMPILATION_CACHE_DIR from this env. No knob excludes
# only sharded programs, so the suite pays repeat compiles instead of
# flaky native crashes. Re-enable (cache dir + min_compile_time 0.5s +
# the env setdefault so node subprocesses share it) only after a
# jaxlib bump proves the round-trip sound for sharded executables.
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

import pytest  # noqa: E402

# tfsan witness lifecycle (no-op unless TFOS_TFSAN=1): thin delegating
# hooks, because pytest honors `pytest_plugins` only in the rootdir
# conftest and this one lives under tests/.
from tests.plugins import tfsan as _tfsan_plugin  # noqa: E402


def pytest_configure(config):
    _tfsan_plugin.configure(config)


def pytest_sessionfinish(session, exitstatus):
    _tfsan_plugin.sessionfinish(session, exitstatus)


@pytest.fixture(scope="session")
def mesh8():
    from tensorflowonspark_tpu.compute.mesh import make_mesh

    return make_mesh({"data": 2, "fsdp": 4})


@pytest.fixture(scope="session")
def mesh_dp():
    from tensorflowonspark_tpu.compute.mesh import make_mesh

    return make_mesh({"data": 8})
