"""End-to-end cluster tests (reference parity: test/test_TFCluster.py).

Local launcher spawns real node processes; the driver feeds them over TCP —
the whole control + data plane on one box, no pod.
"""

import json
import os

import pytest

from tensorflowonspark_tpu.cluster import tfcluster
from tensorflowonspark_tpu.cluster.tfcluster import InputMode

from tests import cluster_fns

pytestmark = pytest.mark.e2e

# Node processes must not initialize a TPU backend in CI.
from tensorflowonspark_tpu.utils.util import cpu_only_env

NODE_ENV = cpu_only_env()


def test_spark_mode_train_sum(tmp_path):
    cluster = tfcluster.run(
        cluster_fns.sum_fn,
        {"out_dir": str(tmp_path)},
        num_executors=2,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        env=NODE_ENV,
    )
    # 4 partitions of 25 numbers each -> round-robin over 2 nodes
    partitions = [list((i,) for i in range(p * 25, (p + 1) * 25)) for p in range(4)]
    cluster.train(partitions)
    cluster.shutdown(timeout=120)

    totals = []
    counts = []
    for i in range(2):
        total, count = open(tmp_path / f"node{i}.txt").read().split()
        totals.append(int(total))
        counts.append(int(count))
    assert sum(counts) == 100
    assert sum(totals) == sum(range(100))


def test_spark_mode_inference(tmp_path):
    cluster = tfcluster.run(
        cluster_fns.square_inference_fn,
        {},
        num_executors=2,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        env=NODE_ENV,
    )
    partitions = [[(i,) for i in range(p * 10, (p + 1) * 10)] for p in range(3)]
    results = cluster.inference(partitions)
    cluster.shutdown(timeout=120)
    assert results == [i**2 for i in range(30)]


def test_inference_stream_backpressure_and_early_close(tmp_path):
    """inference_stream's memory contract: workers stay at most
    2×num_workers partitions ahead of the consumer (backpressure), and
    closing the generator early stops pulling from the source instead
    of draining the whole dataset."""
    cluster = tfcluster.run(
        cluster_fns.square_inference_fn,
        {},
        num_executors=2,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        env=NODE_ENV,
    )
    try:
        pulled = [0]

        def partitions(n):
            for p in range(n):
                pulled[0] += 1
                yield [(p,)]

        # full drain: order preserved across lazily pulled partitions
        out = list(cluster.inference_stream(partitions(20)))
        assert out == [p**2 for p in range(20)]
        assert pulled[0] == 20

        # early close: consume one result, then close. The source must
        # stop near the lookahead bound (head 1 + 2*2 ahead + in-flight
        # slack), nowhere near 50.
        pulled[0] = 0
        stream = cluster.inference_stream(partitions(50))
        first = next(stream)
        stream.close()  # must return promptly, not drain 50 partitions
        assert first == 0
        assert pulled[0] <= 10, f"early close still pulled {pulled[0]}/50"
    finally:
        cluster.shutdown(timeout=120)


def test_inference_stream_surfaces_node_failure(tmp_path):
    """A node dying MID-STREAM must raise out of inference_stream (with
    the ferried traceback), not hang the consumer or silently drop the
    failed partition."""
    cluster = tfcluster.run(
        cluster_fns.poison_inference_fn,
        {},
        num_executors=2,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        env=NODE_ENV,
    )
    try:
        def partitions():
            for p in range(30):
                # the poison record kills whichever node consumes it
                yield [(-1,)] if p == 6 else [(p,)]

        with pytest.raises(Exception) as exc_info:
            # short feed timeout: the healthy path is seconds; a hang
            # here would otherwise burn the default 600s
            list(cluster.inference_stream(partitions(), feed_timeout=60))
        msg = str(exc_info.value).lower()
        # normally the ferried traceback ("poison"); under the node-
        # died-before-ferry race, the driver's lowercase timeout or
        # error-state message
        assert "poison" in msg or "timeout" in msg or "error state" in msg
    finally:
        try:
            cluster.shutdown(timeout=60)
        except Exception:
            pass  # the dead node already surfaced above


def test_tensorflow_mode(tmp_path):
    data_file = tmp_path / "data.txt"
    data_file.write_text("\n".join(str(i) for i in range(50)) + "\n")
    cluster = tfcluster.run(
        cluster_fns.file_reader_fn,
        {"data_file": str(data_file), "out_dir": str(tmp_path)},
        num_executors=2,
        input_mode=InputMode.TENSORFLOW,
        reservation_timeout=120,
        env=NODE_ENV,
    )
    with pytest.raises(RuntimeError):
        cluster.train([[1, 2]])  # feeding is a SPARK-mode operation
    cluster.shutdown(timeout=120)
    vals = [int(open(tmp_path / f"node{i}.txt").read()) for i in range(2)]
    assert sum(vals) == sum(range(50))


def test_error_ferry(tmp_path):
    cluster = tfcluster.run(
        cluster_fns.failing_fn,
        {},
        num_executors=1,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        env=NODE_ENV,
    )
    with pytest.raises(RuntimeError, match="intentional failure"):
        cluster.shutdown(timeout=120)


def test_train_linear_e2e(tmp_path):
    """The minimum end-to-end slice: queue -> DataFeed -> jit step -> export."""
    import numpy as np

    rng = np.random.default_rng(0)
    x = rng.normal(size=512).astype("float32")
    y = 3.0 * x + 1.5
    records = list(zip(x.tolist(), y.tolist()))
    partitions = [records[i::4] for i in range(4)]

    cluster = tfcluster.run(
        cluster_fns.train_linear_fn,
        {"out_dir": str(tmp_path)},
        num_executors=1,
        input_mode=InputMode.SPARK,
        reservation_timeout=180,
        env=NODE_ENV,
    )
    cluster.train(partitions, num_epochs=8)
    cluster.shutdown(timeout=180)

    result = json.load(open(tmp_path / "node0.json"))
    assert abs(result["w"] - 3.0) < 0.2
    assert abs(result["b"] - 1.5) < 0.2


def test_train_stream_micro_batches(tmp_path):
    """Spark Streaming parity: micro-batches fed on arrival via train_stream."""
    cluster = tfcluster.run(
        cluster_fns.sum_fn,
        {"out_dir": str(tmp_path)},
        num_executors=2,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        env=NODE_ENV,
    )

    def stream():
        # 5 micro-batches of 20 records each, arriving over time; empty
        # micro-batches (quiet stream intervals) must be a no-op, not an
        # early-stop signal
        for mb in range(5):
            yield []
            yield [[(i,) for i in range(mb * 20, mb * 20 + 10)],
                   [(i,) for i in range(mb * 20 + 10, (mb + 1) * 20)]]

    cluster.train_stream(stream())
    cluster.shutdown(timeout=120)

    totals, counts = [], []
    for i in range(2):
        total, count = open(tmp_path / f"node{i}.txt").read().split()
        totals.append(int(total))
        counts.append(int(count))
    assert sum(counts) == 100
    assert sum(totals) == sum(range(100))


def test_train_stream_early_stop_on_quiet_stream(tmp_path):
    """Worker-initiated terminate is noticed while the stream is quiet:
    train_stream must return without waiting for the (slow) next yield."""
    import time as _time

    cluster = tfcluster.run(
        cluster_fns.terminate_after_fn,
        {"out_dir": str(tmp_path), "limit": 8},
        num_executors=1,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        env=NODE_ENV,
    )

    def stream():
        yield [[(i,) for i in range(16)]]  # enough to hit the limit
        _time.sleep(120)  # quiet "infinite" stream; must not be awaited
        yield [[(99,)]]

    t0 = _time.monotonic()
    cluster.train_stream(stream())
    elapsed = _time.monotonic() - t0
    cluster.shutdown(timeout=120)
    assert elapsed < 60, f"train_stream did not early-stop ({elapsed:.0f}s)"
    assert int(open(tmp_path / "node0.txt").read()) >= 8


def test_profiler_urls(tmp_path):
    """profiler=True starts a per-node jax.profiler server; roster has URLs."""
    cluster = tfcluster.run(
        cluster_fns.sum_fn,
        {"out_dir": str(tmp_path)},
        num_executors=1,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        profiler=True,
        env=NODE_ENV,
    )
    urls = cluster.profiler_urls()
    cluster.train([[(1,), (2,)]])
    cluster.shutdown(timeout=120)
    assert 0 in urls and ":" in urls[0]


def test_hostlist_launcher_local_shell(tmp_path):
    """HostListLauncher end-to-end with a local shell standing in for ssh:
    exercises the node_main payload path (encode -> CLI -> run_node)."""
    from tensorflowonspark_tpu.cluster.launchers import HostListLauncher

    launcher = HostListLauncher(
        hosts=["hostA", "hostB"], cmd_template="sh -c {command}"
    )
    cluster = tfcluster.run(
        cluster_fns.sum_fn,
        {"out_dir": str(tmp_path)},
        num_executors=2,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        launcher=launcher,
        # The space-containing value proves env quoting survives the
        # template's two shell parses (the ssh-hop failure mode).
        env={**NODE_ENV, "XLA_FLAGS": "--xla_a=1 --xla_b=2"},
    )
    partitions = [[(i,) for i in range(p * 10, (p + 1) * 10)] for p in range(4)]
    cluster.train(partitions)
    cluster.shutdown(timeout=120)
    totals = [
        int(open(tmp_path / f"node{i}.txt").read().split()[0]) for i in range(2)
    ]
    assert sum(totals) == sum(range(40))


def test_eval_node_sidecar(tmp_path):
    """eval_node=True: last node gets the 'evaluator' role and is excluded
    from the data plane; feeds go only to chief/workers."""
    cluster = tfcluster.run(
        cluster_fns.role_aware_fn,
        {"out_dir": str(tmp_path)},
        num_executors=3,
        eval_node=True,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        env=NODE_ENV,
    )
    roles = {n["executor_id"]: n["job_name"] for n in cluster.cluster_info}
    assert roles == {0: "chief", 1: "worker", 2: "evaluator"}
    assert [w["executor_id"] for w in cluster.workers] == [0, 1]
    partitions = [[(i,) for i in range(p * 10, (p + 1) * 10)] for p in range(4)]
    cluster.train(partitions)
    cluster.shutdown(timeout=120)

    out = {}
    for i in range(3):
        role, total = open(tmp_path / f"node{i}.txt").read().split()
        out[i] = (role, int(total))
    assert out[2] == ("evaluator", 0)
    assert out[0][0] == "chief" and out[1][0] == "worker"
    assert out[0][1] + out[1][1] == sum(range(40))


def test_feed_timeout_on_stalled_consumer(tmp_path):
    """Fault injection (SURVEY §4 gap): a consumer that stops pulling must
    surface as a feed TimeoutError in the driver, not a silent hang."""
    cluster = tfcluster.run(
        cluster_fns.stalling_consumer_fn,
        {},
        num_executors=1,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        queue_maxsize=2,
        use_shm_ring=False,  # exercise manager-queue backpressure
        env=NODE_ENV,
    )
    # >> queue_maxsize chunks so the producer must block on the full queue
    partitions = [[(i,) for i in range(4096)]]
    with pytest.raises(TimeoutError, match="feeding partition"):
        cluster.train(partitions, feed_timeout=5)
    with pytest.raises(RuntimeError):  # watchdog force-kill -> nonzero exit
        cluster.shutdown(timeout=5)


def test_node_crash_mid_feed(tmp_path):
    """Fault injection: a node that hard-crashes (no error ferry) must fail
    the train call and shutdown must report the nonzero exit."""
    cluster = tfcluster.run(
        cluster_fns.crashing_consumer_fn,
        {},
        num_executors=1,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        queue_maxsize=2,
        use_shm_ring=False,
        env=NODE_ENV,
    )
    partitions = [[(i,) for i in range(4096)]]
    with pytest.raises((TimeoutError, ConnectionError, EOFError, OSError)):
        cluster.train(partitions, feed_timeout=10)
    with pytest.raises(RuntimeError, match="nonzero"):
        cluster.shutdown(timeout=10)


def test_shm_ring_oversized_chunks(tmp_path):
    """Chunks whose pickle exceeds the ring are split, not dropped: feed
    records far bigger than a 1 MiB ring and check every byte arrives."""
    cluster = tfcluster.run(
        cluster_fns.sum_sizes_fn,
        {"out_dir": str(tmp_path)},
        num_executors=1,
        input_mode=InputMode.SPARK,
        reservation_timeout=120,
        env=NODE_ENV,
        shm_ring_mb=1,
    )
    # 40 records x 200 KiB -> one 512-record chunk would pickle to ~8 MiB
    partitions = [[b"x" * 200_000 for _ in range(20)] for _ in range(2)]
    cluster.train(partitions)
    cluster.shutdown(timeout=120)
    total, count = open(tmp_path / "node0.txt").read().split()
    assert int(count) == 40
    assert int(total) == 40 * 200_000


def test_run_with_restarts_resumes_after_node_crash(tmp_path):
    """Node 0 dies on attempt 1; the supervisor relaunches the whole
    cluster and attempt 2 completes on every node."""
    restarts = tfcluster.run_with_restarts(
        cluster_fns.flaky_checkpoint_fn,
        {"dir": str(tmp_path)},
        num_executors=2,
        input_mode=InputMode.TENSORFLOW,
        max_restarts=2,
        reservation_timeout=120,
        shutdown_timeout=120,
        env=NODE_ENV,
    )
    assert restarts == 1
    assert (tmp_path / "done0").exists() and (tmp_path / "done1").exists()
    # node 0 ran twice, node 1's attempt count depends on how far it got
    assert (tmp_path / "attempts0").read_text() == "2"


def test_run_with_restarts_exhausts(tmp_path):
    with pytest.raises(RuntimeError, match="exited nonzero"):
        tfcluster.run_with_restarts(
            cluster_fns.always_crash_fn,
            {},
            num_executors=1,
            input_mode=InputMode.TENSORFLOW,
            max_restarts=1,
            reservation_timeout=120,
            shutdown_timeout=120,
            env=NODE_ENV,
        )


def test_run_with_restarts_rejects_spark_mode():
    with pytest.raises(ValueError, match="TENSORFLOW"):
        tfcluster.run_with_restarts(
            cluster_fns.sum_fn, {}, num_executors=1, max_restarts=1
        )


def test_as_partitions_tiny_input_feeds_all_workers():
    """len(data) <= num_workers must yield per-record partitions, not one
    big partition that starves every worker but the first."""
    from tensorflowonspark_tpu.cluster.tfcluster import _as_partitions

    assert _as_partitions([(1,), (2,)], 4) == [[(1,)], [(2,)]]
    assert _as_partitions([], 4) == []
    # train default: round-robin (strided per-worker samples)
    assert _as_partitions(list(range(5)), 2) == [[0, 2, 4], [1, 3]]
    # inference: CONTIGUOUS near-equal splits, so partition-order
    # reassembly preserves record order
    assert _as_partitions(list(range(5)), 2, contiguous=True) == [
        [0, 1, 2],
        [3, 4],
    ]
