"""Chunk-columnar wire format tests (``feed/columnar.py``).

Covers the ISSUE-5 acceptance surface:

- codec round-trips for every supported dtype and record kind, with the
  ragged/object/mixed fallbacks that keep non-columnizable data on the
  row-pickle wire;
- CRC/magic/version rejection of corrupt frames;
- zero-copy decode (views over the wire buffer, no payload copies) and
  the refcounted ring-frame lifetime, including under wraparound and a
  deferred close;
- exact batch parity between the columnar and row paths through
  ``DataFeed`` (next_batch + batch_stream) and ``DevicePrefetcher``;
- frame-drop detection: the ``columnar.frame`` failpoint drops a frame
  mid-stream and the consumer's sequence check raises instead of
  silently losing records;
- the framed node-local file format behind ``FileManifest(format=
  "columnar")``.
"""

import gc
import secrets
import threading

import numpy as np
import pytest

from tensorflowonspark_tpu.cluster import manager
from tensorflowonspark_tpu.cluster.marker import EndOfFeed, EndPartition
from tensorflowonspark_tpu.feed import columnar as col
from tensorflowonspark_tpu.feed.datafeed import DataFeed
from tensorflowonspark_tpu.utils import failpoints


@pytest.fixture()
def mgr():
    h = manager.start(
        secrets.token_bytes(16),
        queues=("input", "output", "row", "colr", "rag"),
        mode="local",
    )
    yield h
    h.stop()


@pytest.fixture(autouse=True)
def _disarm():
    yield
    failpoints.disarm_all()


# -- codec round-trips -------------------------------------------------------

DTYPES = [
    np.bool_,
    np.int8,
    np.uint8,
    np.int16,
    np.uint16,
    np.int32,
    np.uint32,
    np.int64,
    np.uint64,
    np.float16,
    np.float32,
    np.float64,
    np.complex64,
    np.complex128,
]


@pytest.mark.parametrize("dtype", DTYPES, ids=[np.dtype(d).name for d in DTYPES])
def test_roundtrip_every_dtype(dtype):
    rng = np.random.default_rng(0)
    base = (rng.random((7, 2, 3)) * 100).astype(dtype)
    records = [{"a": base[i], "b": dtype(base[i].flat[0])} for i in range(7)]
    chunk = col.columnize_records(records)
    assert chunk is not None and chunk.kind == "dict"
    out = col.decode_frame(col.frame_bytes(chunk, qname="input"))
    np.testing.assert_array_equal(out.columns()["a"], base)
    assert out.columns()["a"].dtype == dtype
    np.testing.assert_array_equal(out.columns()["b"], base[:, 0, 0].astype(dtype))


def test_roundtrip_bytes_str_and_kinds():
    # dict with fixed-width bytes + str columns
    records = [{"k": b"ab%d" % i, "s": "s%02d" % i} for i in range(5)]
    out = col.decode_frame(col.frame_bytes(col.columnize_records(records)))
    assert [r["k"] for r in out.rows()] == [r["k"] for r in records]
    assert [str(r["s"]) for r in out.rows()] == [r["s"] for r in records]
    # tuple records keep positional order
    tuples = [(i, np.float32(i) / 2) for i in range(4)]
    out = col.decode_frame(col.frame_bytes(col.columnize_records(tuples)))
    assert out.kind == "tuple"
    assert [
        (int(a), float(b)) for a, b in out.rows()
    ] == [(i, i / 2) for i in range(4)]
    # flat scalar records
    out = col.decode_frame(col.frame_bytes(col.columnize_records([1, 2, 3])))
    assert out.kind == "flat" and [int(v) for v in out.rows()] == [1, 2, 3]


@pytest.mark.parametrize(
    "records",
    [
        [np.zeros(3), np.zeros(4)],  # ragged shapes
        [np.array([object()], dtype=object)],  # object dtype
        [{"a": 1}, {"b": 1}],  # key mismatch
        [(1, 2), (1, 2, 3)],  # arity mismatch
        [{"a": 1}, {"a": "x"}],  # mixed scalar kinds in one column
        [b"a\x00", b"b\x00"],  # trailing NUL (numpy S-dtype trims it)
        ["ab", "abc"],  # variable-width strings
        [{"a": 1}, (1,)],  # mixed record shapes
    ],
)
def test_fallback_to_row_pickle(records):
    assert col.columnize_records(records) is None


def test_corrupt_frames_rejected():
    chunk = col.columnize_records([{"a": np.arange(8)}] * 2)
    data = bytearray(col.frame_bytes(chunk))
    bad_payload = data.copy()
    bad_payload[-1] ^= 0xFF
    with pytest.raises(ValueError, match="payload CRC"):
        col.decode_frame(bytes(bad_payload))
    bad_header = data.copy()
    bad_header[16] ^= 0xFF
    with pytest.raises(ValueError, match="header CRC"):
        col.decode_frame(bytes(bad_header))
    with pytest.raises(ValueError, match="magic"):
        col.decode_frame(b"NOPE" + bytes(data[4:]))
    bad_version = data.copy()
    bad_version[3] = 9
    with pytest.raises(ValueError, match="version"):
        col.decode_frame(bytes(bad_version))


def test_encode_parts_layout():
    """The scatter list concatenates to the one-buffer frame, and every
    column lands 64-aligned relative to the payload start (what lets the
    shm ring serve aligned zero-copy views)."""
    chunk = col.columnize_records(
        [{"a": np.arange(5, dtype=np.int8), "b": 1.5} for _ in range(3)]
    )
    parts = col.encode_parts(chunk, qname="q")
    joined = b"".join(
        p.tobytes() if isinstance(p, np.ndarray) else bytes(p) for p in parts
    )
    assert joined == col.frame_bytes(chunk, qname="q")
    assert col.parts_nbytes(parts) == len(joined)
    decoded = col.decode_frame(joined)
    base = np.frombuffer(joined, np.uint8).__array_interface__["data"][0]
    for arr in decoded.arrays:
        addr = arr.__array_interface__["data"][0]
        assert (addr - base) % col.ALIGN == 0


def test_decode_is_zero_copy():
    chunk = col.columnize_records([{"a": np.arange(64, dtype=np.int64)}] * 4)
    buf = col.frame_bytes(chunk)
    base = np.frombuffer(buf, dtype=np.uint8)
    lo = base.__array_interface__["data"][0]
    out = col.decode_frame(buf)
    for arr in out.arrays:
        addr = arr.__array_interface__["data"][0]
        assert lo <= addr < lo + len(buf), "decoded column was copied"


# -- batch assembly ----------------------------------------------------------


def test_assembler_slices_within_chunk_zero_copy():
    chunk = col.columnize_records(
        [{"x": np.arange(4, dtype=np.float32) + i, "y": i} for i in range(10)]
    )
    asm = col.ColumnAssembler({"x": "x", "y": "y"})
    asm.push(chunk)
    batch = asm.take(4)
    assert batch["x"].shape == (4, 4)
    assert np.shares_memory(batch["x"], chunk.arrays[0])
    batch2 = asm.take(6)
    assert np.shares_memory(batch2["x"], chunk.arrays[0])
    np.testing.assert_array_equal(batch2["y"], np.arange(4, 10))


def test_assembler_mixes_chunks_and_row_lists():
    rows = [(np.full(3, i, np.float32), i) for i in range(6)]
    chunk = col.columnize_records(rows[:4])
    asm = col.ColumnAssembler({"a": "img", "b": "lbl"})
    asm.push(chunk)
    asm.push(rows[4:])  # legacy row-pickle piece
    batch = asm.take(6)
    np.testing.assert_array_equal(batch["lbl"], np.arange(6))
    np.testing.assert_array_equal(
        batch["img"], np.stack([r[0] for r in rows])
    )


def test_assembler_caps_pinned_view_bytes(monkeypatch):
    """Held view-backed pieces past MATERIALIZE_HELD_BYTES are copied
    out (liveness rule 3: one batch bigger than the ring must not pin
    the shm tail forever); owned driver-built pieces never are."""
    monkeypatch.setattr(col.ColumnAssembler, "MATERIALIZE_HELD_BYTES", 4000)
    asm = col.ColumnAssembler({"x": "x"})
    make = lambda: col.columnize_records(
        [{"x": np.arange(512, dtype=np.float32)}] * 2  # 4 KB per piece
    )
    for _ in range(3):
        view = col.decode_frame(col.frame_bytes(make()))
        assert view.is_view
        asm.push(view)  # each piece alone exceeds the 4000 B cap
    assert all(not p.is_view for p in asm._pieces), "cap did not materialize"
    batch = asm.take(6)
    np.testing.assert_array_equal(
        batch["x"], np.tile(np.arange(512, dtype=np.float32), (6, 1))
    )
    owned = make()
    asm.push(owned)
    assert asm._pieces[0] is owned, "owned piece was needlessly copied"


def test_column_batches_fixed_size_and_tail():
    pieces = [
        col.columnize_records([(i, 2 * i) for i in range(7)]),
        [(j, 2 * j) for j in range(7, 11)],  # row list piece
    ]
    out = list(col.column_batches(iter(pieces), 4, 2, {"a": "a", "b": "b"}))
    # 11 records -> 4, 4, tail 3 trimmed to 2 (multiple_of), 1 dropped
    assert [len(b["a"]) for b in out] == [4, 4, 2]
    np.testing.assert_array_equal(
        np.concatenate([b["b"] for b in out]), 2 * np.arange(10)
    )


# -- DataFeed parity: columnar vs row ---------------------------------------


def _records(n=23):
    rng = np.random.default_rng(7)
    return [
        (rng.integers(0, 255, size=8).astype(np.int64), int(i % 10))
        for i in range(n)
    ]


def _put_row_wire(q, records, chunk=6):
    for i in range(0, len(records), chunk):
        q.put(records[i : i + chunk])


def _put_columnar_wire(q, records, chunk=6, stream="s0"):
    seq = 0
    for i in range(0, len(records), chunk):
        ck = col.columnize_records(records[i : i + chunk])
        assert ck is not None
        q.put(
            col.ColumnarFrame(
                col.frame_bytes(ck, qname="input", stream=stream, seq=seq)
            )
        )
        seq += 1


MAPPING = {"image": "image", "label": "label"}


def test_datafeed_next_batch_parity(mgr):
    records = _records()
    q_row, q_colr = mgr.get_queue("row"), mgr.get_queue("colr")
    _put_row_wire(q_row, records)
    q_row.put(EndOfFeed())
    _put_columnar_wire(q_colr, records)
    q_colr.put(EndOfFeed())

    feed_row = DataFeed(mgr, qname_in="row", input_mapping=MAPPING)
    feed_col = DataFeed(mgr, qname_in="colr", input_mapping=MAPPING)
    while True:
        b_row = feed_row.next_batch(5)
        b_col = feed_col.next_batch(5)
        assert set(b_row) == set(b_col) == {"image", "label"}
        for k in b_row:
            assert b_row[k].dtype == b_col[k].dtype
            np.testing.assert_array_equal(b_row[k], b_col[k])
        if feed_row.should_stop():
            assert feed_col.should_stop()
            break


def test_datafeed_batch_stream_parity(mgr):
    records = _records(31)
    q_row, q_colr = mgr.get_queue("row"), mgr.get_queue("colr")
    _put_row_wire(q_row, records, chunk=9)
    q_row.put(EndPartition())
    q_row.put(EndOfFeed())
    _put_columnar_wire(q_colr, records, chunk=9)
    q_colr.put(EndPartition())
    q_colr.put(EndOfFeed())

    rows = list(
        DataFeed(mgr, qname_in="row", input_mapping=MAPPING).batch_stream(8, 2)
    )
    cols = list(
        DataFeed(mgr, qname_in="colr", input_mapping=MAPPING).batch_stream(8, 2)
    )
    assert len(rows) == len(cols)
    for br, bc in zip(rows, cols):
        for k in br:
            assert br[k].dtype == bc[k].dtype
            np.testing.assert_array_equal(br[k], bc[k])


def test_datafeed_batch_stream_after_next_batch_leftover(mgr):
    """batch_stream must drain pieces a prior next_batch call buffered —
    as PIECES, not pre-assembled columns — and preserve record order."""
    records = _records(20)
    q = mgr.get_queue("input")
    _put_columnar_wire(q, records, chunk=7)
    q.put(EndOfFeed())
    feed = DataFeed(mgr, input_mapping=MAPPING)
    head = feed.next_batch(3)  # buffers 4 leftover records of frame 0
    batches = list(feed.batch_stream(4, 2))
    got_imgs = np.concatenate(
        [head["image"]] + [b["image"] for b in batches]
    )
    want = np.stack([r[0] for r in records])
    np.testing.assert_array_equal(got_imgs, want[: len(got_imgs)])
    assert len(got_imgs) == 3 + (20 - 3) // 4 * 4


def test_datafeed_mapping_less_columnar_rows(mgr):
    """Mapping-less consumers get plain record lists back even when the
    wire shipped columns."""
    records = _records(9)
    q = mgr.get_queue("input")
    _put_columnar_wire(q, records, chunk=4)
    q.put(EndOfFeed())
    feed = DataFeed(mgr)
    got = []
    while not feed.should_stop():
        got.extend(feed.next_batch(50))
    assert len(got) == 9
    for (img, lbl), (rimg, rlbl) in zip(got, records):
        np.testing.assert_array_equal(img, rimg)
        assert int(lbl) == rlbl


def test_datafeed_empty_mapping_legacy_contract(mgr):
    """input_mapping={} is degenerate but must keep the pre-columnar
    ``columnize_rows`` contract (empty column dict per batch for dict
    records, loud arity error for tuple records) — not a TypeError off
    a missing assembler."""
    records = [{"a": i} for i in range(5)]
    q = mgr.get_queue("input")
    _put_columnar_wire(q, records, chunk=4)
    q.put(EndOfFeed())
    feed = DataFeed(mgr, input_mapping={})
    while not feed.should_stop():
        assert feed.next_batch(8) == {}

    q2 = mgr.get_queue("row")
    _put_columnar_wire(q2, _records(4), chunk=4)
    q2.put(EndOfFeed())
    feed2 = DataFeed(mgr, qname_in="row", input_mapping={})
    with pytest.raises(ValueError, match="mapping must name every field"):
        feed2.next_batch(8)


def test_datafeed_seq_gap_raises(mgr):
    """A frame dropped mid-stream (armed ``columnar.frame`` drop) must
    surface as a loud sequence-gap error, not silently lost records."""
    records = _records(18)
    q = mgr.get_queue("input")
    _put_columnar_wire(q, records, chunk=6)  # 3 frames, seq 0..2
    q.put(EndOfFeed())
    failpoints.arm("columnar.frame", "drop", count=1)
    feed = DataFeed(mgr, input_mapping=MAPPING)
    with pytest.raises(RuntimeError, match="sequence gap"):
        for _ in range(4):
            feed.next_batch(6)


def test_feed_partition_wire_switch(mgr):
    """columnar=True ships ColumnarFrame chunks; columnar=False pins the
    legacy row-pickle wire (lists) — the operator escape hatch."""
    from tensorflowonspark_tpu.cluster.node import feed_partition

    mgr.set("state", "running")
    records = _records(8)
    fed = feed_partition(mgr, records, qname="colr", chunk=4, columnar=True)
    assert fed == 8
    q = mgr.get_queue("colr")
    first = q.get_nowait()
    assert isinstance(first, col.ColumnarFrame)

    fed = feed_partition(mgr, records, qname="row", chunk=4, columnar=False)
    assert fed == 8
    q = mgr.get_queue("row")
    first = q.get_nowait()
    assert isinstance(first, list) and len(first) == 4

    # non-columnizable records fall back chunk-by-chunk on the same queue
    ragged = [np.zeros(3), np.zeros(4), np.zeros(5), np.zeros(6)]
    fed = feed_partition(mgr, ragged, qname="rag", chunk=4, columnar=True)
    assert fed == 4
    first = mgr.get_queue("rag").get_nowait()
    assert isinstance(first, list) and len(first) == 4


# -- DevicePrefetcher parity -------------------------------------------------


def test_prefetcher_parity_columnar_vs_row(mgr):
    from tensorflowonspark_tpu.feed.prefetch import DevicePrefetcher

    records = _records(26)
    q_row, q_colr = mgr.get_queue("row"), mgr.get_queue("colr")
    _put_row_wire(q_row, records, chunk=7)
    q_row.put(EndOfFeed())
    _put_columnar_wire(q_colr, records, chunk=7)
    q_colr.put(EndOfFeed())

    out = {}
    for qname in ("row", "colr"):
        feed = DataFeed(mgr, qname_in=qname, input_mapping=MAPPING)
        with DevicePrefetcher.from_feed(
            feed, 8, depth=2, multiple_of=2, transform=lambda b: b
        ) as pf:
            out[qname] = [dict(b) for b in pf]
    assert len(out["row"]) == len(out["colr"])
    for br, bc in zip(out["row"], out["colr"]):
        for k in br:
            assert br[k].dtype == bc[k].dtype
            np.testing.assert_array_equal(br[k], bc[k])


# -- ring zero-copy lifetime -------------------------------------------------

shmring = pytest.importorskip("tensorflowonspark_tpu.native.shmring")
needs_native = pytest.mark.skipif(
    not shmring.available(), reason="native shmring unavailable"
)


def _ring_pair(capacity=1 << 14):
    name = f"/tfos_colr_{secrets.token_hex(4)}"
    consumer = shmring.ShmRing.create(name, capacity)
    producer = shmring.ShmRing.open(name)
    return consumer, producer


@needs_native
def test_ring_views_survive_wraparound():
    """Zero-copy views stay intact while the producer wraps the ring
    several times: a held frame pins the tail (backpressure, not
    overwrite), so views are held in a bounded sliding window and each
    is re-verified right before release — any slot reuse under a live
    view would corrupt it."""
    consumer, producer = _ring_pair(1 << 14)  # 16 KiB ring
    frames = 24  # ~1 KiB payload each → ~2 full wraps
    payload = [
        np.arange(128, dtype=np.int64) + 1000 * i for i in range(frames)
    ]

    def produce():
        for i in range(frames):
            ck = col.columnize_records([{"v": payload[i]}])
            producer.push_parts(
                col.encode_parts(ck, stream="w", seq=i), timeout=60
            )
        producer.close_write()

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    held: list = []  # sliding window of live (chunk, seq) views
    n = 0
    while True:
        buf = consumer.pop_frame(timeout=60)
        if buf is None:
            break
        held.append((col.decode_frame(buf), n))
        del buf
        n += 1
        if len(held) > 4:
            chunk, seq = held.pop(0)
            # verify JUST before releasing: it lived through the pushes
            np.testing.assert_array_equal(
                chunk.columns()["v"][0], payload[seq]
            )
            del chunk
    t.join(timeout=60)
    assert n == frames
    assert consumer.outstanding_frames() >= 1
    for chunk, seq in held:
        np.testing.assert_array_equal(chunk.columns()["v"][0], payload[seq])
    del held, chunk
    gc.collect()
    assert consumer.outstanding_frames() == 0
    consumer.close()
    producer.close()


@needs_native
def test_ring_close_deferred_until_views_die():
    consumer, producer = _ring_pair()
    ck = col.columnize_records([{"v": np.arange(32)}])
    producer.push_parts(col.encode_parts(ck), timeout=10)
    buf = consumer.pop_frame(timeout=10)
    assert isinstance(buf, np.ndarray)
    chunk = col.decode_frame(buf)
    consumer.close()  # deferred: a live view pins the mapping
    np.testing.assert_array_equal(chunk.columns()["v"][0], np.arange(32))
    del buf, chunk
    gc.collect()
    assert consumer.outstanding_frames() == 0
    producer.close()


@needs_native
def test_ring_pop_and_pop_frame_interleave_fifo():
    """Copied pops behind an outstanding zero-copy frame must not advance
    the tail past the held slot (FIFO release)."""
    consumer, producer = _ring_pair()
    for i in range(3):
        ck = col.columnize_records([{"v": np.full(16, i, np.int32)}])
        producer.push_parts(col.encode_parts(ck, seq=i), timeout=10)
    producer.close_write()
    first = consumer.pop_frame(timeout=10)  # held view
    chunk0 = col.decode_frame(first)
    assert consumer.pop(timeout=10) is not None  # copied: retires behind
    assert consumer.pop_frame(timeout=10) is not None
    np.testing.assert_array_equal(
        chunk0.columns()["v"][0], np.zeros(16, np.int32)
    )
    del first, chunk0
    gc.collect()
    assert consumer.outstanding_frames() == 0
    consumer.close()
    producer.close()


# -- framed files (manifest path) -------------------------------------------


def test_write_read_frames_roundtrip(tmp_path):
    path = str(tmp_path / "data.colf")
    records = [
        {"x": np.arange(6, dtype=np.float32) * i, "y": i} for i in range(10)
    ]
    assert col.write_frames(path, records, records_per_frame=4) == 10
    chunks = list(col.read_frames(path))
    assert [len(c) for c in chunks] == [4, 4, 2]
    got = [r for c in chunks for r in c.rows()]
    for g, r in zip(got, records):
        np.testing.assert_array_equal(g["x"], r["x"])
        assert int(g["y"]) == r["y"]


def test_write_frames_rejects_ragged(tmp_path):
    with pytest.raises(ValueError, match="not columnizable"):
        col.write_frames(
            str(tmp_path / "bad.colf"), [np.zeros(3), np.zeros(4)]
        )


def test_manifest_columnar_range_and_stream(tmp_path):
    from tensorflowonspark_tpu.feed.manifest import (
        FileManifest,
        read_manifest,
        read_manifest_chunks,
    )

    path = str(tmp_path / "data.colf")
    records = [(np.full(4, i, np.int16), i) for i in range(12)]
    col.write_frames(path, records, records_per_frame=5)
    # record-range slicing across frame boundaries (views, shared mmap)
    m = FileManifest(path, format="columnar", start=3, stop=9)
    got = [int(r[1]) for r in read_manifest(m)]
    assert got == list(range(3, 9))
    assert sum(len(c) for c in read_manifest_chunks(m)) == 6
    # whole file through the row reader
    assert [int(r[1]) for r in read_manifest(FileManifest(path, format="columnar"))] == list(range(12))


def test_manifest_feed_batch_stream_columnar(mgr, tmp_path):
    from tensorflowonspark_tpu.feed.manifest import FileManifest, ManifestFeed

    path = str(tmp_path / "data.colf")
    records = [(np.arange(4, dtype=np.float64) + i, i) for i in range(20)]
    col.write_frames(path, records, records_per_frame=6)
    q = mgr.get_queue("input")
    q.put([FileManifest(path, format="columnar")])
    q.put(EndOfFeed())
    feed = ManifestFeed(DataFeed(mgr))
    batches = list(
        feed.batch_stream(8, multiple_of=2, input_mapping={"a": "x", "b": "y"})
    )
    assert [len(b["y"]) for b in batches] == [8, 8, 4]
    np.testing.assert_array_equal(
        np.concatenate([b["y"] for b in batches]), np.arange(20)
    )
    np.testing.assert_array_equal(
        batches[0]["x"][3], np.arange(4, dtype=np.float64) + 3
    )
