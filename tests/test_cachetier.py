"""tfos.cachetier: the disaggregated read-through cache tier.

Unit tier drives the store (exact keying, byte-budget LRU, per-entry
cap, prefix-exact invalidation, failpoints), the TCP transport
(round-trip, miss-on-timeout against a dead service), the PrefixL2
facade (version/adapter isolation, depth ladder), and the training-
plane frame cache (two concurrent readers cost ONE backing pass; the
grain source's hot-frame LRU regression). Real-tiny-engine legs prove
the serving contract end to end: a prefix prefilled on one replica is
an L2 hit on another with byte-identical output, and a rollout
reclaims EXACTLY the old weights version's entries. The slow chaos e2e
SIGKILLs the cachetier daemon under load — the fleet keeps serving
(cache is an optimization, never a liveness dependency) and the
supervisor respawns it on the same port.
"""

import os
import pickle
import socket
import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu.cachetier import (
    CacheClient,
    CacheServer,
    CacheTier,
    FrameCache,
    LocalClient,
    PrefixL2,
)
from tensorflowonspark_tpu.cachetier.prefix import prefix_key, version_prefix
from tensorflowonspark_tpu.serving.fleet import ServingFleet
from tensorflowonspark_tpu.serving.router import FleetRouter
from tensorflowonspark_tpu.utils import failpoints


@pytest.fixture(autouse=True)
def _no_failpoints():
    yield
    failpoints.disarm_all()


def _free_port() -> int:
    """A port with NO listener (bound then released) — connection
    refused, not filtered."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# -- CacheTier: the store ----------------------------------------------------


def test_tier_exact_keying_and_lru_eviction():
    tier = CacheTier(capacity_bytes=64)
    assert tier.fill("prefix", "a", b"x" * 24)
    assert tier.fill("prefix", "b", b"y" * 24)
    # exact bytes back; a hit refreshes recency
    assert tier.lookup("prefix", "a") == b"x" * 24
    # namespaces partition the key space
    assert tier.lookup("frames", "a") is None
    # third fill overflows: the LRU victim is "b" (a was refreshed)
    assert tier.fill("prefix", "c", b"z" * 24)
    assert tier.lookup("prefix", "b") is None
    assert tier.lookup("prefix", "a") == b"x" * 24
    assert tier.lookup("prefix", "c") == b"z" * 24
    st = tier.stats()
    assert st["entries"] == 2
    assert st["bytes"] == 48
    assert st["evictions"] == 1
    assert st["fills"] == 3
    assert st["hits"] == 3 and st["misses"] == 2


def test_tier_per_entry_cap_and_capacity_knob():
    tier = CacheTier(capacity_bytes=100)
    # a blob over half the budget is refused outright — admitting it
    # would evict most of the working set for one entry
    assert not tier.fill("frames", "huge", b"x" * 51)
    assert tier.lookup("frames", "huge") is None
    assert tier.fill("frames", "a", b"x" * 40)
    assert tier.fill("frames", "b", b"y" * 40)
    assert tier.stats()["bytes"] == 80
    # the autotune actuation path: shrinking evicts immediately
    assert tier.capacity_bytes == 100
    tier.set_capacity(50)
    st = tier.stats()
    assert st["capacity_bytes"] == 50
    assert st["bytes"] <= 50
    assert st["entries"] == 1
    with pytest.raises(ValueError):
        tier.set_capacity(0)


def test_tier_invalidate_is_prefix_exact():
    tier = CacheTier(capacity_bytes=1 << 20)
    tier.fill("prefix", "v0|a|1,2", b"old")
    tier.fill("prefix", "v0|b|1,2", b"old2")
    tier.fill("prefix", "v1|a|1,2", b"new")
    tier.fill("frames", "v0|decoy", b"frame")
    # drops EXACTLY the v0 prefix keys: other versions and other
    # namespaces are untouched
    assert tier.invalidate("prefix", "v0|") == 2
    assert tier.lookup("prefix", "v0|a|1,2") is None
    assert tier.lookup("prefix", "v1|a|1,2") == b"new"
    assert tier.lookup("frames", "v0|decoy") == b"frame"
    assert tier.invalidate("prefix", "v0|") == 0


def test_tier_failpoints_degrade_never_corrupt():
    tier = CacheTier(capacity_bytes=20)
    assert tier.fill("x", "k", b"val")
    # a dropped lookup IS a miss, not a hang or an error
    failpoints.arm("cachetier.lookup", "drop", count=1)
    assert tier.lookup("x", "k") is None
    assert tier.lookup("x", "k") == b"val"
    # a dropped fill is refused (the entry simply is not cached)
    failpoints.arm("cachetier.fill", "drop", count=1)
    assert not tier.fill("x", "k2", b"v2")
    assert tier.lookup("x", "k2") is None
    # a dropped evict round leaves the store transiently over budget;
    # the next fill resumes eviction — never corrupts
    failpoints.arm("cachetier.evict", "drop")
    assert tier.fill("x", "a", b"x" * 10)
    assert tier.fill("x", "b", b"y" * 10)
    assert tier.stats()["bytes"] > 20  # over budget, by design
    failpoints.disarm_all()
    assert tier.fill("x", "c", b"z")
    assert tier.stats()["bytes"] <= 20


def test_tier_get_frame_read_through(tmp_path):
    path = str(tmp_path / "backing.bin")
    payload = bytes(range(256)) * 4
    with open(path, "wb") as f:
        f.write(payload)
    tier = CacheTier(capacity_bytes=1 << 20)
    # miss: the pread happens IN the service and fills the store
    assert tier.get_frame(path, 16, 64) == payload[16:80]
    st = tier.stats()
    assert st["backing_read_bytes"] == 64
    # hit: no second backing read
    assert tier.get_frame(path, 16, 64) == payload[16:80]
    assert tier.stats()["backing_read_bytes"] == 64
    # failure is a fallback signal, never an exception
    assert tier.get_frame(str(tmp_path / "gone.bin"), 0, 8) is None
    # short read (span past EOF) is refused, not returned torn
    assert tier.get_frame(path, len(payload) - 4, 64) is None


# -- TCP transport -----------------------------------------------------------


def test_cache_server_roundtrip(tmp_path):
    tier = CacheTier(capacity_bytes=1 << 20)
    srv = CacheServer(tier).start()
    cl = CacheClient(srv.address)
    try:
        # fills are fire-and-forget: wait for the filler to drain
        cl.fill("prefix", "v0||1,2,3", b"blob-bytes")
        assert _wait(lambda: tier.stats()["fills"] == 1)
        assert cl.lookup("prefix", "v0||1,2,3", timeout_s=2.0) == b"blob-bytes"
        assert cl.lookup("prefix", "nope", timeout_s=2.0) is None
        # read-through frames over the wire
        path = str(tmp_path / "f.bin")
        with open(path, "wb") as f:
            f.write(b"0123456789")
        assert cl.get_frame(path, 2, 6, timeout_s=2.0) == b"234567"
        st = cl.stats()
        assert st["entries"] == 2
        assert st["backing_read_bytes"] == 6
        assert cl.invalidate("prefix", "v0|") == 1
        assert cl.lookup("prefix", "v0||1,2,3", timeout_s=2.0) is None
    finally:
        cl.close()
        srv.close()


def test_lookup_miss_on_timeout_never_hangs():
    # no listener: connection refused — a miss in bounded time
    cl = CacheClient(f"127.0.0.1:{_free_port()}")
    try:
        t0 = time.monotonic()
        assert cl.lookup("prefix", "k", timeout_s=0.2) is None
        # down-backoff: the immediate retry short-circuits
        assert cl.lookup("prefix", "k", timeout_s=0.2) is None
        assert time.monotonic() - t0 < 2.0
        # fills and stats degrade the same way (no exception, no hang)
        cl.fill("prefix", "k", b"v")
        assert cl.stats() is None
    finally:
        cl.close()


def test_lookup_bounded_after_server_death():
    tier = CacheTier(capacity_bytes=1 << 20)
    srv = CacheServer(tier).start()
    cl = CacheClient(srv.address)
    try:
        cl.fill("prefix", "k", b"v")
        assert _wait(lambda: tier.stats()["fills"] == 1)
        assert cl.lookup("prefix", "k", timeout_s=2.0) == b"v"
        srv.close()
        t0 = time.monotonic()
        assert cl.lookup("prefix", "k", timeout_s=0.3) is None
        assert time.monotonic() - t0 < 3.0
    finally:
        cl.close()
        srv.close()


# -- PrefixL2: the serving-plane facade --------------------------------------


def test_prefix_l2_version_and_adapter_isolation():
    tier = CacheTier(capacity_bytes=1 << 20)
    l2 = PrefixL2(LocalClient(tier), chunk=4, lookup_timeout_s=1.0)
    try:
        leaves = [
            np.arange(12, dtype=np.float32).reshape(3, 4),
            np.ones((2, 2), np.int32) * 7,
        ]
        toks = list(range(100, 108))
        l2.offer(toks, leaves, None, "v0")
        assert _wait(lambda: tier.stats()["fills"] == 1)
        # longest-prefix hit at the stored depth, bit-exact round-trip
        hit = l2.lookup(toks + [1, 2], None, "v0")
        assert hit is not None
        got, depth = hit
        assert depth == 8
        assert len(got) == 2
        for a, b in zip(got, leaves):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(a, b)
        # the exactness contract: another adapter or another weights
        # version NEVER sees this entry (its keys are simply different)
        assert l2.lookup(toks, "lora-a", "v0") is None
        assert l2.lookup(toks, None, "v1") is None
        # rollout reclamation is exact by key prefix
        assert l2.invalidate_version("v0") == 1
        assert l2.lookup(toks, None, "v0") is None
        st = l2.stats()
        assert st["l2_hits"] == 1
        assert st["l2_offered"] == 1
    finally:
        l2.close()


def test_prefix_l2_probes_the_boundary_ladder():
    """Entries land at L1 boundary-insert depths (chunk * 2**k); a
    longer prompt's lookup probes exactly that ladder and returns the
    LONGEST stored prefix."""
    tier = CacheTier(capacity_bytes=1 << 20)
    l2 = PrefixL2(LocalClient(tier), chunk=4, lookup_timeout_s=1.0)
    try:
        toks = list(range(16))
        l2.offer(toks[:4], [np.zeros(2, np.float32)], None, "v0")
        l2.offer(toks[:8], [np.ones(2, np.float32)], None, "v0")
        assert _wait(lambda: tier.stats()["fills"] == 2)
        got, depth = l2.lookup(toks[:13], None, "v0")
        assert depth == 8
        np.testing.assert_array_equal(got[0], np.ones(2, np.float32))
        # key construction matches the module helpers exactly
        assert tier.lookup("prefix", prefix_key("v0", None, toks[:8])) is not None
        assert prefix_key("v0", None, [1, 2]).startswith(version_prefix("v0"))
    finally:
        l2.close()


def test_l2_offer_dedup_skips_repeat_publishes_and_self_heals():
    """A key's value is a pure function of (version, adapter, tokens),
    so a repeat offer buys nothing and costs a host copy + pickle per
    request — the dedup window must swallow it. And the window must
    SELF-HEAL: after the tier loses the entry (rollout, daemon respawn,
    LRU pressure), an observed lookup miss re-arms the offer."""
    tier = CacheTier(capacity_bytes=1 << 20)
    l2 = PrefixL2(LocalClient(tier), chunk=4, lookup_timeout_s=1.0)
    try:
        toks = [11, 12, 13, 14]
        leaves = [np.zeros(2, np.float32)]
        l2.offer(toks, leaves, None, "v0")
        assert _wait(lambda: tier.stats()["fills"] == 1)
        l2.offer(toks, leaves, None, "v0")
        time.sleep(0.15)  # a real repeat fill would land well inside this
        st = l2.stats()
        assert st["l2_offered"] == 1
        assert st["l2_offer_dedups"] == 1
        assert tier.stats()["fills"] == 1
        # tier drops the entry; the next lookup MISSES and clears the
        # probed keys from the window, so the offer publishes again
        assert l2.invalidate_version("v0") == 1
        assert l2.lookup(toks + [9, 9], None, "v0") is None
        l2.offer(toks, leaves, None, "v0")
        assert _wait(lambda: tier.stats()["fills"] == 2)
        assert l2.stats()["l2_offered"] == 2
    finally:
        l2.close()


# -- frame cache: the training plane -----------------------------------------


def _write_framed(tmp_path, name="data.colf", n=24, per_frame=4):
    from tensorflowonspark_tpu.feed import columnar as col

    path = str(tmp_path / name)
    records = [
        {"x": np.arange(6, dtype=np.float32) + i, "y": np.int64(i)}
        for i in range(n)
    ]
    col.write_frames(path, records, records_per_frame=per_frame)
    return path, records


def test_two_readers_cost_one_backing_pass(tmp_path):
    """The tentpole claim for training: N co-located readers over one
    framed dataset fetch each frame from backing storage ~once — the
    read-through pread happens in the shared service."""
    from tensorflowonspark_tpu.data.grain_source import (
        ColumnarFrameDataSource,
    )
    from tensorflowonspark_tpu.feed.columnar import scan_frames

    path, records = _write_framed(tmp_path, n=32, per_frame=4)
    spans = [span for _, span, n in scan_frames(path) if n]
    payload = sum(spans)
    tier = CacheTier(capacity_bytes=1 << 20)
    srcs = [
        ColumnarFrameDataSource(path, frame_cache=FrameCache(LocalClient(tier)))
        for _ in range(2)
    ]
    out = [[None] * len(records) for _ in srcs]

    def read_all(ri, order):
        for i in order:
            out[ri][i] = srcs[ri][i]

    # opposed iteration orders: the readers touch mostly-disjoint
    # frames first, then each serves the other's fills from the tier
    threads = [
        threading.Thread(target=read_all, args=(0, range(len(records)))),
        threading.Thread(
            target=read_all, args=(1, range(len(records) - 1, -1, -1))
        ),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
        assert not t.is_alive()
    # every record, byte-identical from both readers
    for ri in range(2):
        for i, r in enumerate(records):
            np.testing.assert_array_equal(out[ri][i]["x"], r["x"])
            assert int(out[ri][i]["y"]) == i
    st = tier.stats()
    # ~1x the dataset: exactly one backing read per frame, modulo the
    # rare race where both readers miss one frame at the crossing point
    assert payload <= st["backing_read_bytes"] <= payload + 2 * max(spans)
    assert st["hits"] > 0  # the second reader actually hit the tier
    # the facade is process-local: dropped on pickle (grain workers)
    clone = pickle.loads(pickle.dumps(srcs[0]))
    assert clone._frame_cache is None
    assert len(clone) == len(records)


def test_read_frames_via_cache_is_identical(tmp_path):
    from tensorflowonspark_tpu.feed.columnar import read_frames, scan_frames

    path, records = _write_framed(tmp_path, n=12, per_frame=5)
    tier = CacheTier(capacity_bytes=1 << 20)
    fc = FrameCache(LocalClient(tier))
    plain = [r for c in read_frames(path) for r in c.rows()]
    cached = [r for c in read_frames(path, frame_cache=fc) for r in c.rows()]
    assert len(plain) == len(cached) == 12
    for a, b in zip(plain, cached):
        np.testing.assert_array_equal(a["x"], b["x"])
        assert int(a["y"]) == int(b["y"])
    payload = sum(span for _, span, n in scan_frames(path) if n)
    assert tier.stats()["backing_read_bytes"] == payload
    # a second cached pass is served from the tier: zero new backing IO
    list(read_frames(path, frame_cache=fc))
    assert tier.stats()["backing_read_bytes"] == payload


def test_shard_reader_threads_frame_cache(tmp_path):
    from tensorflowonspark_tpu.feed.datafeed import ReplayCursor
    from tensorflowonspark_tpu.feed.ingest import ShardReader
    from tensorflowonspark_tpu.feed.manifest import FileManifest

    path, records = _write_framed(tmp_path, n=10, per_frame=4)
    tier = CacheTier(capacity_bytes=1 << 20)
    m = FileManifest(path, format="columnar")
    reader = ShardReader([m], frame_cache=FrameCache(LocalClient(tier)))
    pieces = list(reader.pieces(ReplayCursor()))
    assert sum(len(pc) for pc in pieces) == 10
    assert tier.stats()["fills"] > 0  # the drain went through the tier


def test_grain_lru_keeps_hot_frame(tmp_path):
    """Satellite regression: the decoded-frame cache is true LRU — a
    sampler's hot frame survives eviction pressure (FIFO silently
    evicted it and re-decoded every touch)."""
    from tensorflowonspark_tpu.data.grain_source import (
        ColumnarFrameDataSource,
    )

    path, _ = _write_framed(tmp_path, n=6, per_frame=1)  # 6 frames
    src = ColumnarFrameDataSource(path)
    assert src._CACHE_FRAMES == 4
    for i in range(4):  # fill the cache: frames 0..3
        src[i]
    key0 = tuple(src._frames[0][:2])  # (file_idx, offset) of frame 0
    hot = src._cache[key0]
    src[0]  # re-touch: LRU refreshes frame 0's recency
    src[4]  # pressure: evicts frame 1 (the LRU head), NOT frame 0
    assert key0 in src._cache
    assert src._cache[key0] is hot  # same decode — never re-paid
    key1 = tuple(src._frames[1][:2])
    assert key1 not in src._cache


# -- router: affinity demotes to a locality hint -----------------------------


class _StubMetrics:
    def render(self):
        return "# TYPE stub_up gauge\nstub_up 1\n"


class _StubEngine:
    """Minimal engine-shaped double for placement tests (the full
    scriptable version lives in tests/test_fleet.py)."""

    def __init__(self):
        self.live = True
        self.ready = True
        self.calls = []
        self.closed = False
        self.metrics = _StubMetrics()

    def warmup(self):
        pass

    def health(self):
        return {"live": self.live, "ready": self.ready}

    def stats(self):
        return {
            "slots": 2,
            "slots_busy": 0,
            "queue_depth": 0,
            "watchdog_fires": 0,
            "admitted": len(self.calls),
            "completed": len(self.calls),
        }

    def unresolved(self):
        return 0

    def submit_many(self, prompts, max_new_tokens, **kw):
        self.calls.append(list(prompts))
        return [[7] * min(int(max_new_tokens), 3) for _ in prompts]

    def close(self, drain=False, drain_timeout=300.0):
        self.closed = True
        self.live = False
        self.ready = False


def _stub_fleet(n=2, **kw):
    made = []

    def factory():
        e = _StubEngine()
        made.append(e)
        return e

    kw.setdefault("probe_interval", 5.0)
    kw.setdefault("warmup", False)
    kw.setdefault("drain_timeout", 2.0)
    return ServingFleet(factory=factory, replicas=n, **kw), made


def _load_and_extend(router, stubs, base, extra_load):
    """Warm ``base`` on one replica, load that replica by
    ``extra_load`` outstanding, then submit the extension; returns
    (warm_rid, other_rid)."""
    router.submit(base, 2)
    warm = 0 if stubs[0].calls else 1
    other = 1 - warm
    with router._lock:
        router._outstanding[other] = 0
        router._outstanding[warm] = (
            router._outstanding.get(warm, 0) + extra_load
        )
    router.submit(base + [9, 10], 2)
    return warm, other


def test_affinity_bypasses_overloaded_warm_replica_with_l2():
    """With a prefix L2 behind the fleet, affinity is a locality HINT:
    when the warm replica's load skew exceeds the slack, placement
    yields to the least-loaded replica (the miss is recoverable from
    the shared tier) and accounts a bypass."""
    fleet, stubs = _stub_fleet(2, prefix_l2="inproc")
    try:
        router = FleetRouter(fleet)
        warm, other = _load_and_extend(router, stubs, [5, 6, 7, 8], 4)
        st = router.stats()["router"]
        assert st["affinity_bypasses"] >= 1
        assert len(stubs[other].calls) == 1  # the extension moved
        assert len(stubs[warm].calls) == 1
        assert (
            'router_affinity_total{outcome="bypass"}'
            in router.metrics_text()
        )
    finally:
        fleet.close()


def test_affinity_still_wins_under_slack_and_without_l2():
    # comparable load (skew <= slack): warm routing still wins even
    # with an L2 — locality is free when it costs nothing
    fleet, stubs = _stub_fleet(2, prefix_l2="inproc")
    try:
        router = FleetRouter(fleet)
        warm, other = _load_and_extend(router, stubs, [5, 6, 7, 8], 1)
        st = router.stats()["router"]
        assert st["affinity_hits"] >= 1
        assert st["affinity_bypasses"] == 0
        assert len(stubs[warm].calls) == 2
    finally:
        fleet.close()
    # no L2 configured: affinity keeps its placement-correctness role —
    # the warm replica is the ONLY place the prefix exists
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        warm, other = _load_and_extend(router, stubs, [5, 6, 7, 8], 4)
        st = router.stats()["router"]
        assert st["affinity_bypasses"] == 0
        assert len(stubs[warm].calls) == 2
    finally:
        fleet.close()


def test_affinity_load_slack_is_tunable():
    fleet, stubs = _stub_fleet(2, prefix_l2="inproc")
    try:
        router = FleetRouter(fleet, affinity_load_slack=100.0)
        warm, other = _load_and_extend(router, stubs, [5, 6, 7, 8], 4)
        assert router.stats()["router"]["affinity_bypasses"] == 0
        assert len(stubs[warm].calls) == 2
    finally:
        fleet.close()


# -- fleet spec / knob plane -------------------------------------------------


def test_l2_spec_normalization():
    from tensorflowonspark_tpu.serving.fleet import _normalize_l2_spec

    assert _normalize_l2_spec(None) is None
    spec = _normalize_l2_spec("inproc")
    assert spec["mode"] == "inproc"
    assert spec["capacity_bytes"] == 256 << 20
    assert spec["lookup_timeout_s"] == 0.05
    spec = _normalize_l2_spec({"mode": "spawn", "capacity_bytes": 1 << 20})
    assert spec["mode"] == "spawn" and spec["capacity_bytes"] == 1 << 20
    with pytest.raises(ValueError, match="mode"):
        _normalize_l2_spec("tcp")
    with pytest.raises(ValueError, match="capacity"):
        _normalize_l2_spec({"capacity_bytes": 0})
    with pytest.raises(ValueError, match="prefix_l2"):
        _normalize_l2_spec(17)


def test_cache_budget_policy_grows_on_rising_hit_rate():
    """Satellite: the autotune knob grows the byte budget while the
    hit-rate is rising AND memory headroom exists, backs off hard when
    headroom is gone, and actuates the tier directly."""
    from tensorflowonspark_tpu.autotune.policies import cache_budget_policy
    from tensorflowonspark_tpu.obs.history import History
    from tensorflowonspark_tpu.obs.registry import Registry

    head = {"v": 0.5}
    tier = CacheTier(capacity_bytes=1 << 20)
    knob, pol = cache_budget_policy(
        tier,
        lo_bytes=1 << 20,
        hi_bytes=8 << 20,
        step_bytes=1 << 20,
        window_s=10.0,
        headroom_fn=lambda: head["v"],
    )
    assert knob.name == "cachetier.capacity_bytes"
    # the knob actuates the store (the SANCTIONED set-capacity path)
    knob.apply(2 << 20)
    assert tier.capacity_bytes == 2 << 20
    assert knob.get() == 2 << 20

    r = Registry()
    hits = r.counter("cachetier_hits_total", "t")
    misses = r.counter("cachetier_misses_total", "t")
    hist = History(source="t")
    # prior window (90, 100]: 10% hit share
    hits.inc(1)
    misses.inc(9)
    hist.scrape_registry(r, t=95.0)
    # recent window (100, 110]: 80% — rising
    hits.inc(8)
    misses.inc(2)
    hist.scrape_registry(r, t=105.0)
    assert pol.hint(hist, 110.0) == 1  # rising + headroom: grow
    head["v"] = 0.05  # below min_headroom_frac/2: shed NOW
    assert pol.hint(hist, 110.0) == -1
    head["v"] = None  # unreadable meminfo: hold still
    assert pol.hint(hist, 110.0) == 0
    # falling hit share: hold even with headroom
    head["v"] = 0.5
    hits.inc(1)
    misses.inc(9)
    hist.scrape_registry(r, t=115.0)
    assert pol.hint(hist, 120.0) == 0


# -- real-engine e2e ---------------------------------------------------------


@pytest.fixture(scope="module")
def tiny():
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models.llama import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(dtype=jnp.float32, remat=False)
    model = Llama(cfg)
    p0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    p1 = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, model, p0, p1


def _tiny_fleet(tiny, **kw):
    from tensorflowonspark_tpu.serving import ContinuousBatcher

    cfg, model, p0, p1 = tiny

    def factory():
        return ContinuousBatcher(
            model, p0, slots=2, prompt_widths=(8,),
            prefill_chunk=4, prefix_cache=4,
        )

    kw.setdefault("probe_interval", 0.5)
    kw.setdefault("warmup", False)
    kw.setdefault("drain_timeout", 5.0)
    return ServingFleet(factory=factory, replicas=2, **kw)


def test_fleet_l2_cross_replica_hit_is_byte_exact(tiny):
    """The tentpole serving claim: a prefix prefilled by replica 0 is
    an L2 hit on replica 1, and the hit-path output is IDENTICAL to a
    cold engine's — the cache changes cost, never results."""
    from tensorflowonspark_tpu.serving import ContinuousBatcher

    cfg, model, p0, p1 = tiny
    fleet = _tiny_fleet(tiny, prefix_l2="inproc")
    try:
        views = fleet.views()
        base = [5, 6, 7, 8, 9, 10, 11, 12]
        got0 = views[0]["handle"].submit_many([base], 3)
        # the fire-and-forget filler publishes off the scheduler thread
        assert _wait(
            lambda: (fleet.cache_stats() or {}).get("entries", 0) > 0
        )
        ext = base + [13, 14]
        got1 = views[1]["handle"].submit_many([ext], 3)
        st1 = views[1]["handle"].stats()
        assert st1["prefix_l2_hits"] >= 1
        ref = ContinuousBatcher(
            model, p0, slots=2, prompt_widths=(8,),
            prefill_chunk=4, prefix_cache=4,
        )
        try:
            want = ref.submit_many([ext], 3)
        finally:
            ref.close()
        assert got1 == want
        assert got0  # replica 0 itself served fine
        # fleet-level reclamation drops every v0 entry
        assert fleet.invalidate_prefix_version("v0") > 0
        assert (fleet.cache_stats() or {}).get("entries") == 0
    finally:
        fleet.close()


def test_l2_hit_reconstructs_the_offered_cache(tiny):
    """Regression: a STEPPED single-row cache's scalar planes round-
    trip through the L2 as batch-1 rows — shape ``(1,)`` against the
    template's ``()``. Reconstruct must fold that axis and apply the
    hit; rejecting it silently re-prefills from token 0, every "hit"
    byte-exact and worthless (hit counters and output-equality tests
    all stay green while the tier saves zero compute)."""
    from tensorflowonspark_tpu.serving import ContinuousBatcher

    cfg, model, p0, p1 = tiny
    tier = CacheTier(capacity_bytes=32 << 20)
    eng = ContinuousBatcher(
        model, p0, slots=2, prompt_widths=(8,),
        prefill_chunk=4, prefix_cache=4,
    )
    try:
        eng.attach_prefix_l2(
            PrefixL2(LocalClient(tier), chunk=4, lookup_timeout_s=1.0)
        )
        base = [5, 6, 7, 8, 9, 10, 11, 12]
        eng.submit_many([base], 2)
        assert _wait(lambda: tier.stats()["fills"] > 0)
        hit = eng._prefix_l2.lookup(
            base + [13, 14], None, eng._weights_version
        )
        assert hit is not None and hit[1] >= 4
        # the payload an engine actually publishes must reconstruct
        assert eng._l2_reconstruct(hit[0]) is not None
    finally:
        eng.close()


def test_rollout_reclaims_exactly_the_old_version(tiny):
    """Rollout under a warm L2: after the fleet converges on v1, the
    tier holds ZERO v0 prefix entries — and ONLY those were dropped
    (other namespaces and the new version's keys survive)."""
    import jax

    from tensorflowonspark_tpu.serving.rollout import RolloutController

    cfg, model, p0, p1 = tiny
    fleet = _tiny_fleet(
        tiny, probe_interval=5.0, drain_timeout=10.0, prefix_l2="inproc"
    )
    ctl = RolloutController(
        fleet, drain_timeout=10.0, verify_timeout=30.0,
        warmup_probe=False,
    )
    try:
        base = [5, 6, 7, 8, 9, 10, 11, 12]
        for v in fleet.views():
            v["handle"].submit_many([base], 2)
        assert _wait(
            lambda: (fleet.cache_stats() or {}).get("entries", 0) > 0
        )
        # sentinels that must SURVIVE the reclamation: another
        # namespace, and the incoming version's own key space
        fleet.cache_tier.fill("frames", "decoy", b"frame-bytes")
        fleet.cache_tier.fill("prefix", "v1|sentinel|1,2", b"new-bytes")
        assert (
            ctl.publish(jax.tree.map(np.asarray, p1), version="v1")
            == "completed"
        )
        with fleet.cache_tier._lock:
            keys = list(fleet.cache_tier._entries)
        stale = [
            k for k in keys if k[0] == "prefix" and k[1].startswith("v0|")
        ]
        assert stale == []  # the old version is GONE
        assert ("frames", "decoy") in keys  # ...and nothing else is
        assert ("prefix", "v1|sentinel|1,2") in keys
        for v in fleet.views():
            assert v["handle"].stats()["weights_version"] == "v1"
    finally:
        fleet.close()


@pytest.mark.slow
def test_fleet_sigkill_cachetier_daemon_under_load(tiny, tmp_path):
    """Chaos e2e: SIGKILL the cachetier daemon mid-load. The fleet
    keeps serving with ZERO failed or hung requests (every lookup
    degrades to a bounded-latency miss), and the supervisor respawns
    the daemon on the SAME port so cached client addresses stay
    valid."""
    from tensorflowonspark_tpu.obs import flightrec

    rec = flightrec.install(
        str(tmp_path / "flightrec-cachetier.json"), process="cachetier-test"
    )
    fleet = _tiny_fleet(
        tiny,
        probe_interval=0.3,
        prefix_l2={"mode": "spawn", "capacity_bytes": 32 << 20},
    )
    router = FleetRouter(fleet)
    results: dict[int, object] = {}
    N = 8

    def one(i):
        try:
            results[i] = (
                "ok",
                router.submit([20 + i, 3, 4, 5, 6, 7, 8, 9], 4),
            )
        except BaseException as e:  # noqa: BLE001 - the verdict
            results[i] = ("err", e)

    try:
        with fleet._cache_lock:
            daemon = fleet._cache_proc
        assert daemon is not None and daemon.poll() is None
        addr_before = fleet.cachetier_address
        # warm traffic so the tier is live before the kill
        router.submit([11, 12, 13, 14, 15, 16, 17, 18], 3)
        threads = [
            threading.Thread(target=one, args=(i,), daemon=True)
            for i in range(N)
        ]
        for t in threads:
            t.start()
        os.kill(daemon.pid, 9)
        # ZERO failed, ZERO hung: the cache is never a liveness
        # dependency — every in-flight request resolves ok
        for t in threads:
            t.join(timeout=120.0)
            assert not t.is_alive(), "a request hung on a dead cache"
        assert set(results) == set(range(N))
        for kind, payload in results.values():
            assert kind == "ok", payload
            assert payload
        # the fleet still serves fresh traffic while the tier is down
        assert router.submit([30, 31, 32, 33, 34, 35, 36, 37], 3)
        # the supervisor respawns the daemon on the ORIGINAL port and
        # the admin client reconnects (down-backoff included)
        assert _wait(
            lambda: fleet._cache_respawns >= 1
            and fleet.cache_stats() is not None,
            timeout=30.0,
            interval=0.2,
        ), "cachetier daemon was not respawned"
        assert fleet.cachetier_address == addr_before
        kinds = [e["kind"] for e in rec.snapshot("test")["events"]]
        assert "cachetier_spawn" in kinds
        assert "cachetier_respawn" in kinds
    finally:
        router.close()
        rec.stop()
        with flightrec._install_lock:
            flightrec._recorder = None
