"""Native layer tests: TFRecord codec (native + fallback + TF interop) and
the shared-memory feed ring."""

import struct
import threading

import pytest

from tensorflowonspark_tpu import native
from tensorflowonspark_tpu.native import tfrecord as ntfr
from tensorflowonspark_tpu.native.shmring import ShmRing

RECORDS = [b"hello", b"", b"x" * 100_000, bytes(range(256)) * 7]


@pytest.fixture(scope="module")
def lib():
    lib = native.load_library()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def test_tfrecord_roundtrip_native(lib, tmp_path):
    p = str(tmp_path / "a.tfrecord")
    with ntfr.TFRecordWriter(p) as w:
        assert w.native
        for r in RECORDS:
            w.write(r)
    assert list(ntfr.read_records(p)) == RECORDS


def test_tfrecord_python_fallback_matches_native(lib, tmp_path):
    """Fallback writer produces byte-identical files to the native writer."""
    p1, p2 = str(tmp_path / "n.tfrecord"), str(tmp_path / "p.tfrecord")
    with ntfr.TFRecordWriter(p1) as w:
        for r in RECORDS:
            w.write(r)
    w2 = ntfr.TFRecordWriter.__new__(ntfr.TFRecordWriter)
    w2._lib, w2._h, w2._path = None, None, p2
    w2._f = open(p2, "wb")
    for r in RECORDS:
        w2.write(r)
    w2.close()
    assert open(p1, "rb").read() == open(p2, "rb").read()
    assert list(ntfr._py_read_records(p1)) == RECORDS


def test_tfrecord_tf_interop(lib, tmp_path):
    """TF's reader accepts our files and vice versa (format authority)."""
    tf = pytest.importorskip("tensorflow")
    ours = str(tmp_path / "ours.tfrecord")
    theirs = str(tmp_path / "theirs.tfrecord")
    with ntfr.TFRecordWriter(ours) as w:
        for r in RECORDS:
            w.write(r)
    got = [bytes(x) for x in tf.data.TFRecordDataset(ours).as_numpy_iterator()]
    assert got == RECORDS
    with tf.io.TFRecordWriter(theirs) as w:
        for r in RECORDS:
            w.write(r)
    assert list(ntfr.read_records(theirs)) == RECORDS


def test_tfrecord_crc_native_matches_python(lib):
    for r in RECORDS + [b"q" * 13]:
        assert lib.tfr_masked_crc32c(r, len(r)) == ntfr._py_masked_crc(r)


def test_tfrecord_detects_corruption(lib, tmp_path):
    p = str(tmp_path / "c.tfrecord")
    with ntfr.TFRecordWriter(p) as w:
        w.write(b"payload-payload-payload")
    blob = bytearray(open(p, "rb").read())
    blob[14] ^= 0xFF  # flip a payload byte
    open(p, "wb").write(bytes(blob))
    with pytest.raises(OSError, match="corrupt"):
        list(ntfr.read_records(p))
    # truncated file
    open(p, "wb").write(bytes(blob[:10]))
    with pytest.raises(OSError, match="truncated"):
        list(ntfr._py_read_records(p))


def test_shmring_order_and_wraparound(lib):
    cons = ShmRing.create("/tfos_t_wrap", capacity=1 << 16)  # 64 KiB: wraps
    prod = ShmRing.open("/tfos_t_wrap")
    try:
        sent = [struct.pack("<I", i) + b"v" * (i * 131 % 3000) for i in range(500)]

        def producer():
            for r in sent:
                prod.push(r, timeout=10)
            prod.close_write()

        t = threading.Thread(target=producer)
        t.start()
        got = []
        while (r := cons.pop(timeout=10)) is not None:
            got.append(r)
        t.join()
        assert got == sent
    finally:
        prod.close()
        cons.close()


def test_shmring_backpressure_timeout(lib):
    cons = ShmRing.create("/tfos_t_bp", capacity=1 << 12)
    prod = ShmRing.open("/tfos_t_bp")
    try:
        with pytest.raises(TimeoutError):
            for _ in range(100):  # no consumer: ring fills, push times out
                prod.push(b"z" * 1024, timeout=0.2)
        with pytest.raises(ValueError):
            prod.push(b"z" * (1 << 13), timeout=0.2)  # bigger than the ring
    finally:
        prod.close()
        cons.close()


def test_shmring_pop_timeout_and_close(lib):
    cons = ShmRing.create("/tfos_t_to", capacity=1 << 12)
    prod = ShmRing.open("/tfos_t_to")
    try:
        with pytest.raises(TimeoutError):
            cons.pop(timeout=0.2)
        prod.push(b"last", timeout=1)
        prod.close_write()
        assert cons.pop(timeout=1) == b"last"  # drain completes after close
        assert cons.pop(timeout=1) is None
    finally:
        prod.close()
        cons.close()


def test_shmring_stale_segment_recreated(lib):
    """create() must clobber a leftover segment from a crashed run."""
    a = ShmRing.create("/tfos_t_stale", capacity=1 << 12)
    # simulate crash: no close/unlink, just recreate
    b = ShmRing.create("/tfos_t_stale", capacity=1 << 12)
    prod = ShmRing.open("/tfos_t_stale")
    prod.push(b"fresh", timeout=1)
    assert b.pop(timeout=1) == b"fresh"
    prod.close()
    b.close()
    a._owner = False  # the old handle must not unlink the new segment
    a.close()


def test_index_file_native_matches_python(lib, tmp_path):
    from tensorflowonspark_tpu.data import grain_source

    p = str(tmp_path / "idx.tfrecord")
    with ntfr.TFRecordWriter(p) as w:
        for r in RECORDS:
            w.write(r)
    native_idx = grain_source._index_file_native(p)
    assert native_idx is not None
    # force the pure-Python scan for comparison
    import unittest.mock as mock

    with mock.patch.object(
        grain_source, "_index_file_native", return_value=None
    ):
        py_idx = grain_source._index_file(p)
    assert native_idx == py_idx
    assert [n for _, n in native_idx] == [len(r) for r in RECORDS]


def test_index_file_native_detects_corruption(lib, tmp_path):
    from tensorflowonspark_tpu.data import grain_source

    p = str(tmp_path / "bad.tfrecord")
    with ntfr.TFRecordWriter(p) as w:
        w.write(b"payload-one")
        w.write(b"payload-two")
    raw = bytearray(open(p, "rb").read())

    truncated = str(tmp_path / "trunc.tfrecord")
    open(truncated, "wb").write(raw[:-3])
    with pytest.raises(ValueError, match="truncated"):
        grain_source._index_file_native(truncated)

    corrupt = str(tmp_path / "corrupt.tfrecord")
    flipped = bytearray(raw)
    flipped[0] ^= 0xFF  # corrupt the first record's length field
    open(corrupt, "wb").write(flipped)
    with pytest.raises(ValueError, match="corrupt"):
        grain_source._index_file_native(corrupt)
