"""docs/WIRE.md schema-table drift gate.

The table claims to list EVERY declared wire schema. Claims drift;
this gate doesn't: it ``ast.literal_eval``s the ``WIRE_SCHEMAS``
table (the same import-free read the WR analyzer uses) and diffs both
directions against the doc rows — a schema added without a row fails,
and so does a row naming a schema the table no longer declares, or a
row whose version/compat/transport went stale.
"""

import ast
import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WIRE_PY = os.path.join(
    ROOT, "tensorflowonspark_tpu", "cluster", "wire.py"
)
DOC = os.path.join(ROOT, "docs", "WIRE.md")

# | `name` | vN | compat | transport |
_ROW = re.compile(
    r"^\|\s*`([a-zA-Z0-9_.]+)`\s*\|\s*v(\d+)\s*\|"
    r"\s*(frozen|add_only_optional)\s*\|\s*([a-z]+)\s*\|"
)


def _declared_schemas() -> dict:
    with open(WIRE_PY, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=WIRE_PY)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "WIRE_SCHEMAS"
            for t in node.targets
        ):
            return ast.literal_eval(node.value)
    raise AssertionError("WIRE_SCHEMAS literal not found in wire.py")


def _doc_rows() -> dict:
    out = {}
    with open(DOC, encoding="utf-8") as f:
        for line in f:
            m = _ROW.match(line.strip())
            if m:
                assert m.group(1) not in out, (
                    f"duplicate doc row for {m.group(1)}"
                )
                out[m.group(1)] = {
                    "version": int(m.group(2)),
                    "compat": m.group(3),
                    "transport": m.group(4),
                }
    return out


def test_every_schema_has_a_doc_row():
    declared, rows = _declared_schemas(), _doc_rows()
    missing = sorted(set(declared) - set(rows))
    assert not missing, (
        f"undocumented wire schemas (add rows to docs/WIRE.md): "
        f"{missing}"
    )


def test_no_stale_doc_rows():
    declared, rows = _declared_schemas(), _doc_rows()
    stale = sorted(set(rows) - set(declared))
    assert not stale, (
        f"docs/WIRE.md rows for undeclared schemas (remove them): "
        f"{stale}"
    )


def test_doc_rows_match_declarations():
    declared, rows = _declared_schemas(), _doc_rows()
    for name in sorted(set(declared) & set(rows)):
        sc, row = declared[name], rows[name]
        assert row["version"] == sc["version"], (
            f"{name}: doc says v{row['version']}, table declares "
            f"v{sc['version']}"
        )
        assert row["compat"] == sc["compat"], (
            f"{name}: doc says {row['compat']}, table declares "
            f"{sc['compat']}"
        )
        assert row["transport"] == sc.get("transport"), (
            f"{name}: doc says {row['transport']}, table declares "
            f"{sc.get('transport')}"
        )


def test_table_is_nonempty():
    rows = _doc_rows()
    assert len(rows) >= 30, f"suspiciously small doc table: {len(rows)}"
