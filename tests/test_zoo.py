"""Model-zoo factory tests: every registered name trains one step."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflowonspark_tpu.compute.mesh import make_mesh
from tensorflowonspark_tpu.models import zoo


def test_names_and_unknown():
    assert "resnet50" in zoo.names()
    assert "inception_v3" in zoo.names()
    assert "vgg16" in zoo.names()
    assert "llama2_7b" in zoo.names()
    with pytest.raises(KeyError, match="unknown zoo model"):
        zoo.build("alexnet")


@pytest.mark.slow  # one XLA compile per zoo entry
@pytest.mark.parametrize("name", zoo.names())
def test_every_entry_trains_one_step_tiny(name):
    entry = zoo.build(name, tiny=True, num_classes=10)
    batch = entry.make_input(4)
    mesh = make_mesh({"data": -1, "fsdp": 2})
    tx = optax.sgd(0.1)

    if entry.has_batch_stats:
        variables = entry.model.init(
            jax.random.PRNGKey(0), batch["image"], train=True
        )
        params, stats = variables["params"], variables["batch_stats"]
        params = jax.tree.map(
            jax.device_put, params, entry.param_shardings(params, mesh)
        )
        loss = entry.make_loss()
        (l, _), g = jax.value_and_grad(loss, has_aux=True)(
            params, stats, batch
        )
    else:
        key = next(iter(batch))
        params = entry.model.init(jax.random.PRNGKey(0), batch[key])[
            "params"
        ]
        params = jax.tree.map(
            jax.device_put, params, entry.param_shardings(params, mesh)
        )
        loss = entry.make_loss()
        l, g = jax.value_and_grad(loss)(params, batch)
    assert np.isfinite(float(l))
    upd, _ = tx.update(g, tx.init(params))
    new_params = optax.apply_updates(params, upd)
    assert jnp.isfinite(jax.tree.leaves(new_params)[0]).all()


def test_full_size_configs_have_expected_scale():
    """Non-tiny entries must describe the real architectures; verified
    via eval_shape (no memory materialized)."""
    sizes = {}
    for name in ("resnet50", "vgg16", "llama2_7b"):
        entry = zoo.build(name)
        batch = entry.make_input(1)
        key = "image" if "image" in batch else "tokens"
        x = batch[key] if key == "image" else batch[key][:, :-1]
        shapes = jax.eval_shape(
            lambda xx, e=entry: e.model.init(jax.random.PRNGKey(0), xx),
            x,
        )
        n = sum(
            int(np.prod(s.shape))
            for s in jax.tree.leaves(shapes["params"])
        )
        sizes[name] = n
    assert 2.4e7 < sizes["resnet50"] < 2.7e7  # ~25.6M
    assert 1.3e8 < sizes["vgg16"] < 1.45e8  # ~138M
    assert 6.5e9 < sizes["llama2_7b"] < 7.0e9  # ~6.74B
