"""Serving fleet: health-routed replicas, failover, draining, shedding.

Tier-1 tests drive the router/fleet policy machinery against scripted
stub engines (deterministic, no compiles) plus a few real-engine and
real-HTTP-server legs; the two slow chaos e2e tests SIGKILL a
subprocess replica under streaming load and drive 2x overload with
shedding on.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from tensorflowonspark_tpu.obs import registry as obs_registry
from tensorflowonspark_tpu.serving.engine import (
    DeadlineExceeded,
    EngineOverloaded,
    EngineWedged,
)
from tensorflowonspark_tpu.serving.fleet import (
    DEAD,
    DRAINING,
    READY,
    STARTING,
    FleetOverloaded,
    FleetUnavailable,
    ReplicaGone,
    ServingFleet,
)
from tensorflowonspark_tpu.serving.router import FleetRouter
from tensorflowonspark_tpu.utils import failpoints


# -- scripted stub engines ---------------------------------------------------


class _StubMetrics:
    def render(self):
        return "# TYPE stub_up gauge\nstub_up 1\n"


class _StubStream:
    """Scripted stream: yields ``tokens``, optionally raising ``error``
    after ``error_after`` yields."""

    def __init__(self, tokens, error=None, error_after=0):
        self._tokens = list(tokens)
        self._error = error
        self._error_after = error_after
        self._i = 0
        self.result = None
        self.logprobs = None
        self.closed = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._error is not None and self._i >= self._error_after:
            raise self._error
        if self._i >= len(self._tokens):
            self.result = list(self._tokens)
            raise StopIteration
        t = self._tokens[self._i]
        self._i += 1
        return t

    def close(self):
        self.closed = True


class _StubEngine:
    """Engine-shaped scriptable double: the router/fleet surface
    (submit_many/stream/stats/health/unresolved/close/metrics) with
    injectable failures and health flips."""

    def __init__(self):
        self.live = True
        self.ready = True
        self.submit_error = None  # raised by submit_many when set
        self.stream_script = None  # () -> _StubStream
        self.stats_extra = {}
        self.closed = False
        self.calls = []
        self.metrics = _StubMetrics()

    def warmup(self):
        pass

    def health(self):
        return {"live": self.live, "ready": self.ready}

    def stats(self):
        base = {
            "slots": 2,
            "slots_busy": 0,
            "queue_depth": 0,
            "watchdog_fires": 0,
            "admitted": len(self.calls),
            "completed": len(self.calls),
        }
        base.update(self.stats_extra)
        return base

    def unresolved(self):
        return 0

    def submit_many(self, prompts, max_new_tokens, **kw):
        self.calls.append(list(prompts))
        if self.submit_error is not None:
            raise self.submit_error
        return [[7] * min(int(max_new_tokens), 3) for _ in prompts]

    stream_error = None  # raised by stream() at open when set

    def stream(self, tokens, max_new_tokens, **kw):
        self.calls.append([list(tokens)])
        if self.stream_error is not None:
            raise self.stream_error
        if self.stream_script is not None:
            return self.stream_script()
        return _StubStream(list(range(min(int(max_new_tokens), 4))))

    def close(self, drain=False, drain_timeout=300.0):
        self.closed = True
        self.live = False
        self.ready = False


def _stub_fleet(n=2, **kw):
    """Fleet over stub engines; returns (fleet, stubs) where stubs[rid]
    is the LATEST engine behind that seat (respawns append)."""
    made = []

    def factory():
        e = _StubEngine()
        made.append(e)
        return e

    kw.setdefault("probe_interval", 0.1)
    kw.setdefault("warmup", False)
    kw.setdefault("respawn_backoff_s", 0.01)
    kw.setdefault("drain_timeout", 2.0)
    fleet = ServingFleet(factory=factory, replicas=n, **kw)
    return fleet, made


def _wait_states(fleet, want, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fleet.states() == want:
            return
        time.sleep(0.02)
    assert fleet.states() == want


@pytest.fixture(autouse=True)
def _no_failpoints():
    yield
    failpoints.disarm_all()


# -- construction / basics ---------------------------------------------------


def test_fleet_requires_exactly_one_replica_kind():
    with pytest.raises(ValueError, match="exactly one"):
        ServingFleet()
    with pytest.raises(ValueError, match="exactly one"):
        ServingFleet(factory=lambda: None, spawn_argv=["x"])
    with pytest.raises(ValueError, match="replicas"):
        ServingFleet(factory=lambda: None, replicas=0)


def test_placement_deterministic_least_loaded_tiebreak_rid():
    """With equal load the lowest rid wins; outstanding dispatches
    shift the next placement to the other replica — deterministic."""
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        p0 = router._place([1, 2], 0, None, set())
        assert p0["rid"] == 0
        # p0 not resolved: outstanding makes replica 1 the next pick
        p1 = router._place([3, 4], 0, None, set())
        assert p1["rid"] == 1
        router._resolve(0, "ok")
        router._resolve(1, "ok")
    finally:
        fleet.close()


def test_router_requests_route_and_resolve():
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        out = router.submit([1, 2, 3], 3)
        assert out == [7, 7, 7]
        st = router.stats()
        assert st["router"]["outstanding"] == {}
        assert st["fleet"]["ready"] == 2
        # distinct prompts spread by rid tie-break (sequential, both
        # idle) — both land on replica 0
        assert stubs[0].calls
    finally:
        fleet.close()


def test_prefix_affinity_routes_to_warm_replica():
    """A prompt extending an already-dispatched prompt follows it to
    the same replica (adapter-bucketed longest-prefix probe), and the
    hit is accounted on /stats + the router_affinity_total metric."""
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        base = [5, 6, 7, 8]
        router.submit(base, 2)
        first_rid = 0 if stubs[0].calls else 1
        # load the OTHER replica so least-loaded would pick it — the
        # affinity hit must override the load signal
        other = 1 - first_rid
        with router._lock:
            router._outstanding[other] = 0
        with router._lock:
            router._outstanding[first_rid] = (
                router._outstanding.get(first_rid, 0) + 3
            )
        router.submit(base + [9, 10], 2)
        st = router.stats()["router"]
        assert st["affinity_hits"] >= 1
        # the extension landed on the SAME replica despite its load
        assert len(stubs[first_rid].calls) == 2
        assert len(stubs[other].calls) == 0
        text = router.metrics_text()
        assert 'router_affinity_total{outcome="hit"}' in text
    finally:
        fleet.close()


def test_affinity_is_adapter_bucketed():
    """The same prompt under another adapter is NOT an affinity hit —
    a prefix computed under one LoRA adapter is not warm for
    another."""
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        router.submit([5, 6, 7], 2, adapter=0)
        hits0 = router.stats()["router"]["affinity_hits"]
        router.submit([5, 6, 7], 2, adapter=0)
        assert router.stats()["router"]["affinity_hits"] == hits0 + 1
        # different adapter: miss (stub engines accept any adapter)
        router.submit([5, 6, 7, 8], 2, adapter=0)
        misses = router.stats()["router"]["affinity_misses"]
        router.submit([5, 6, 7, 8], 2, adapter=3)
        assert router.stats()["router"]["affinity_misses"] == misses + 1
    finally:
        fleet.close()


# -- shedding ----------------------------------------------------------------


def test_deadline_admission_sheds_with_retry_after(tmp_path):
    from tensorflowonspark_tpu.obs import flightrec

    rec = flightrec.install(str(tmp_path / "rec.json"), process="t")
    fleet, stubs = _stub_fleet(2)
    try:
        # 10s estimated service time, no queue: est completion = 10s
        router = FleetRouter(fleet, service_time_hint_s=10.0)
        with pytest.raises(FleetOverloaded) as ei:
            router.submit([1], 2, deadline_s=5.0)
        assert ei.value.retry_after >= 1.0
        st = router.stats()["router"]
        assert st["shed"] == {"deadline": 1}
        # a feasible deadline admits
        assert router.submit([1], 2, deadline_s=30.0) == [7, 7]
        text = router.metrics_text()
        assert 'router_shed_total{reason="deadline"}' in text
        # shedding is an incident: on the flight record
        kinds = [e["kind"] for e in rec.snapshot("t")["events"]]
        assert "fleet_shed" in kinds
    finally:
        fleet.close()
        rec.stop()
        with flightrec._install_lock:
            flightrec._recorder = None


def test_deadline_admission_prefers_feasible_replica_over_affinity():
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet, service_time_hint_s=1.0)
        base = [4, 4, 4]
        router.submit(base, 2)  # replica 0 becomes the warm one
        with router._lock:
            # drop the near-zero stub-duration EWMA so the 1s hint is
            # the estimate the admission math uses
            router._est_req_s.clear()
        # replica 0's queue makes the deadline infeasible there
        stubs[0].stats_extra = {"queue_depth": 50, "slots": 1}
        fleet.probe_now()
        out = router.submit(base + [5], 2, deadline_s=3.0)
        assert out == [7, 7]
        # it went to replica 1 (feasibility beat affinity)
        assert len(stubs[1].calls) == 1
    finally:
        fleet.close()


def test_queue_full_on_every_replica_sheds_fleet_overloaded():
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        for s in stubs:
            s.submit_error = EngineOverloaded("request queue full (1)")
        with pytest.raises(FleetOverloaded, match="queue"):
            router.submit([1], 2)
        st = router.stats()["router"]
        assert st["shed"].get("queue_full") == 1
        # the replicas were NOT reported unhealthy (overload is not
        # death): both still ready
        assert fleet.states() == {0: READY, 1: READY}
    finally:
        fleet.close()


def test_full_fleet_drain_sheds_503_class():
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        fleet.begin_drain()
        with pytest.raises(FleetUnavailable):
            router.submit([1], 2)
        assert router.stats()["router"]["shed"] == {"drain": 1}
        assert router.health()["ready"] is False
        assert router.health()["live"] is True
    finally:
        fleet.close()


# -- failover ----------------------------------------------------------------


def test_submit_failover_once_on_wedged_replica():
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet, ewma_alpha=1.0)
        stubs[0].submit_error = EngineWedged("no scheduler progress")
        out = router.submit([1, 2], 3)
        assert out == [7, 7, 7]
        st = router.stats()["router"]
        assert st["failovers"] == 1
        # the wedged replica was reported: it drains (and respawns)
        _wait_states(fleet, {0: READY, 1: READY}, timeout=10.0)
        assert len(stubs) == 3  # a FRESH engine behind seat 0
        assert stubs[0].closed
        text = router.metrics_text()
        assert "router_failover_total 1" in text
        assert 'fleet_respawns_total{outcome="ok"} 1' in text
    finally:
        fleet.close()


def test_submit_failover_is_once_then_terminal():
    fleet, stubs = _stub_fleet(2, respawn=False)
    try:
        router = FleetRouter(fleet)
        for s in stubs:
            s.submit_error = EngineWedged("wedged")
        with pytest.raises(EngineWedged):
            router.submit([1], 2)
        assert router.stats()["router"]["failovers"] == 1
    finally:
        fleet.close()


def test_dispatch_drop_failpoint_fails_over_then_loud_terminal():
    """fleet.dispatch 'drop' = a dispatch lost in flight: one drop is
    absorbed by failover; dropping both attempts is a LOUD ReplicaGone
    terminal — never a hang."""
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        failpoints.arm("fleet.dispatch", "drop", count=1)
        assert router.submit([1], 2) == [7, 7]
        assert router.stats()["router"]["failovers"] == 1
        _wait_states(fleet, {0: READY, 1: READY})
        failpoints.arm("fleet.dispatch", "drop", count=2)
        with pytest.raises(ReplicaGone, match="dropped"):
            router.submit([2], 2)
    finally:
        fleet.close()


def test_stream_failover_pre_first_token_midstream_terminal():
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        # replica 0: dies BEFORE the first token -> transparent
        # failover onto replica 1's healthy stream
        stubs[0].stream_script = lambda: _StubStream(
            [], error=ReplicaGone("severed"), error_after=0
        )
        s = router.stream([1, 2], 3)
        assert list(s) == [0, 1, 2]
        assert router.stats()["router"]["failovers"] == 1
        _wait_states(fleet, {0: READY, 1: READY})
        # mid-stream failure: tokens were consumed -> exactly one
        # terminal, no retry
        for stub in stubs:
            if not stub.closed:
                stub.stream_script = lambda: _StubStream(
                    [9, 9], error=EngineWedged("wedged"), error_after=2
                )
        s2 = router.stream([3, 4], 5)
        got = []
        with pytest.raises(EngineWedged):
            for t in s2:
                got.append(t)
        assert got == [9, 9]
    finally:
        fleet.close()


def test_stream_close_cancels_and_resolves():
    fleet, stubs = _stub_fleet(1)
    try:
        router = FleetRouter(fleet)
        s = router.stream([1], 4)
        next(s)
        s.close()
        st = router.stats()["router"]
        assert st["outstanding"] == {}
        text = router.metrics_text()
        assert 'outcome="cancelled"' in text
    finally:
        fleet.close()


# -- health plane / supervision ----------------------------------------------


def test_probe_misses_flip_draining_and_respawn_gated_on_readiness():
    fleet, stubs = _stub_fleet(2, miss_limit=2)
    try:
        stubs[0].live = False  # dead engine: probes miss
        for _ in range(2):
            fleet.probe_now()
        # draining (or already respawning/ready again) — never READY
        # with a dead engine behind it
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if len(stubs) == 3:  # respawn built a fresh engine
                break
            time.sleep(0.02)
        assert len(stubs) == 3
        _wait_states(fleet, {0: READY, 1: READY})
        text = fleet.metrics.render()
        assert 'fleet_replica_state{replica="0",state="ready"} 1' in text
        assert 'fleet_probe_misses_total{replica="0"} 2' in text
    finally:
        fleet.close()


def test_watchdog_fire_delta_flips_draining():
    """The EngineWedged signal: a watchdog_fires increase in /stats
    flips the replica to DRAINING within one probe round."""
    fleet, stubs = _stub_fleet(2)
    try:
        fleet.probe_now()  # baseline watchdog_fires=0
        stubs[1].stats_extra = {"watchdog_fires": 1}
        fleet.probe_now()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(stubs) < 3:
            time.sleep(0.02)
        assert stubs[1].closed  # old engine retired
        _wait_states(fleet, {0: READY, 1: READY})
        assert fleet.stats()["seats"]["1"]["respawns"] == 1
    finally:
        fleet.close()


def test_not_ready_replica_is_not_routable():
    fleet, stubs = _stub_fleet(2, miss_limit=100)  # no drain from misses
    try:
        router = FleetRouter(fleet)
        stubs[0].ready = False  # e.g. warmup regressed / draining
        fleet.probe_now()
        # probe counted a miss but did not flip; placement must still
        # avoid it? state is READY (miss_limit high) so the router may
        # pick it — health() readiness is the fleet-level signal:
        h = fleet.health()
        assert h["replicas"]["0"]["ready"] is False
        assert h["ready"] is True  # replica 1 carries the fleet
    finally:
        fleet.close()


def test_spawn_failpoint_exhausts_respawn_budget_to_dead():
    fleet, stubs = _stub_fleet(2, max_respawns=2)
    try:
        router = FleetRouter(fleet)
        failpoints.arm("fleet.replica_spawn", "raise")
        fleet.report_failure(0, "test kill")
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if fleet.states()[0] == DEAD:
                break
            time.sleep(0.05)
        assert fleet.states()[0] == DEAD
        assert fleet.stats()["seats"]["0"]["respawns"] == 2
        # the fleet keeps serving on the surviving replica
        assert router.submit([1], 2) == [7, 7]
        text = fleet.metrics.render()
        assert 'fleet_replica_state{replica="0",state="dead"} 1' in text
        assert 'fleet_respawns_total{outcome="failed"}' in text
    finally:
        fleet.close()


def test_respawn_disabled_marks_dead_and_survivor_serves():
    fleet, stubs = _stub_fleet(2, respawn=False)
    try:
        router = FleetRouter(fleet)
        fleet.report_failure(0, "gone")
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and fleet.states()[0] != DEAD:
            time.sleep(0.02)
        assert fleet.states()[0] == DEAD
        assert router.submit([1], 2) == [7, 7]
        assert len(stubs[1].calls) == 1
        # both seats down -> FleetUnavailable, not a hang
        fleet.report_failure(1, "gone too")
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and fleet.states()[1] != DEAD:
            time.sleep(0.02)
        with pytest.raises(FleetUnavailable):
            router.submit([2], 2)
    finally:
        fleet.close()


def test_replica_reset_drops_affinity_for_respawned_seat():
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        router.submit([1, 2, 3], 2)
        assert len(router._affinity) == 1
        fleet.report_failure(0, "kill")
        _wait_states(fleet, {0: READY, 1: READY})
        with router._lock:
            # the respawned seat's entries are gone (cold engine)
            assert router._affinity.lookup([1, 2, 3, 4], 0) is None
    finally:
        fleet.close()


def test_respawn_budget_counts_consecutive_failures_not_successes():
    """REGRESSION (review): the DEAD budget counts CONSECUTIVE failed
    spawns — a seat that successfully respawns more than max_respawns
    times over its lifetime never goes DEAD."""
    fleet, stubs = _stub_fleet(2, max_respawns=2)
    try:
        for round_ in range(3):  # 3 successful respawns > budget of 2
            fleet.report_failure(0, f"incident {round_}")
            _wait_states(fleet, {0: READY, 1: READY}, timeout=15.0)
        seat = fleet.stats()["seats"]["0"]
        assert seat["state"] == READY
        assert seat["respawns"] == 3  # lifetime attempts still counted
    finally:
        fleet.close()


def test_stale_generation_failure_does_not_drain_respawned_seat():
    """REGRESSION (review): a request-path failure verdict about a
    seat's OLD engine (generation already replaced) must not drain the
    fresh one."""
    fleet, stubs = _stub_fleet(2)
    try:
        fleet.report_failure(0, "first death")  # gen 0 -> respawn
        _wait_states(fleet, {0: READY, 1: READY}, timeout=15.0)
        respawns = fleet.stats()["seats"]["0"]["respawns"]
        # a straggler request from generation 0 reports its failure
        fleet.report_failure(0, "stale verdict", generation=0)
        time.sleep(0.3)
        assert fleet.states()[0] == READY
        assert fleet.stats()["seats"]["0"]["respawns"] == respawns
    finally:
        fleet.close()


def test_single_probe_miss_does_not_flap_reported_health():
    """REGRESSION (review): one unanswered probe below miss_limit must
    not flip the cached /healthz verdict to dead while the replica
    still serves — the drain threshold is the debounce."""
    fleet, stubs = _stub_fleet(1, miss_limit=3)
    try:
        fleet.probe_now()  # positive baseline
        failpoints.arm("fleet.replica_probe", "raise", count=1)
        fleet.probe_now()  # one miss
        assert fleet.stats()["seats"]["0"]["misses"] == 1
        h = fleet.health()
        assert h["live"] is True and h["ready"] is True, h
    finally:
        fleet.close()


def test_probe_failpoint_counts_misses():
    fleet, stubs = _stub_fleet(1, miss_limit=3)
    try:
        failpoints.arm("fleet.replica_probe", "raise", count=2)
        fleet.probe_now()
        fleet.probe_now()
        assert fleet.stats()["seats"]["0"]["misses"] == 2
        fleet.probe_now()  # disarmed: healthy probe resets
        assert fleet.stats()["seats"]["0"]["misses"] == 0
        assert fleet.states()[0] == READY
    finally:
        fleet.close()


def test_stream_open_retries_overloaded_replica_then_429_class():
    """REGRESSION (review): stream/submit parity — an overloaded
    replica at stream OPEN (no 200 committed yet) is retried once on
    another replica; both overloaded raises the 429-class
    FleetOverloaded, not a bare EngineOverloaded 503."""
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        stubs[0].stream_error = EngineOverloaded("request queue full")
        s = router.stream([1, 2], 3)
        assert list(s) == [0, 1, 2]  # replica 1 served it
        assert fleet.states() == {0: READY, 1: READY}  # not a death
        stubs[1].stream_error = EngineOverloaded("request queue full")
        with pytest.raises(FleetOverloaded):
            router.stream([3, 4], 3)
        assert router.stats()["router"]["shed"].get("queue_full") == 1
    finally:
        fleet.close()


def test_http_stream_torn_line_is_replica_gone():
    """REGRESSION (review): a torn NDJSON line from a SIGKILLed
    subprocess replica must surface as the failover-eligible
    ReplicaGone, not a JSONDecodeError that bypasses failure
    reporting."""
    from tensorflowonspark_tpu.serving.fleet import _HTTPStream

    class _TornResp:
        def readline(self):
            return b'{"tok'  # the replica died mid-write

    class _NullConn:
        def close(self):
            pass

    s = object.__new__(_HTTPStream)
    s._rid = 7
    s._yield_logprobs = False
    s._done = False
    s.result = None
    s.logprobs = None
    s._resp = _TornResp()
    s._conn = _NullConn()
    with pytest.raises(ReplicaGone, match="severed mid-line"):
        next(s)
    assert s._done  # terminal: iteration is over, no hang


def test_stream_terminal_failover_does_not_double_resolve():
    """REGRESSION (review): a stream that fails over and then finds no
    replica left releases its outstanding count exactly once — close()
    after the terminal must not eat a concurrent request's count or
    record a second outcome."""
    fleet, stubs = _stub_fleet(1, respawn=False)
    try:
        router = FleetRouter(fleet)
        # a concurrent request holds one outstanding on replica 0
        router._place([9, 9], 0, None, set())
        stubs[0].stream_script = lambda: _StubStream(
            [], error=ReplicaGone("severed"), error_after=0
        )
        s = router.stream([1, 2], 3)
        with pytest.raises(ReplicaGone):
            next(s)
        s.close()
        with router._lock:
            # only the stream's own outstanding was released
            assert router._outstanding.get(0) == 1
        text = router.metrics_text()
        assert 'outcome="cancelled"' not in text
        assert 'outcome="failover"' in text
    finally:
        fleet.close()


def test_fleet_cold_start_all_failed_raises_root_cause():
    """REGRESSION (review): a factory that always fails must fail
    construction with ITS error (not an AttributeError from a
    half-built close())."""

    def bad_factory():
        raise RuntimeError("boom at spawn")

    with pytest.raises(RuntimeError, match="boom at spawn"):
        ServingFleet(
            factory=bad_factory, replicas=2, warmup=False,
            respawn=False, probe_interval=0.1,
        )


def test_cold_start_partial_failure_enters_respawn_without_wait():
    """REGRESSION (review): with wait_ready=False a failed cold start
    must not strand the seat in STARTING forever — it enters the
    ordinary respawn path and comes up."""
    made = []

    def flaky_factory():
        e = _StubEngine()
        made.append(e)
        if len(made) == 1:
            raise RuntimeError("first spawn fails")
        return e

    fleet = ServingFleet(
        factory=flaky_factory, replicas=1, warmup=False,
        wait_ready=False, probe_interval=0.1,
        respawn_backoff_s=0.01, drain_timeout=1.0,
    )
    try:
        _wait_states(fleet, {0: READY}, timeout=15.0)
        assert len(made) == 2
    finally:
        fleet.close()


# -- metrics merge -----------------------------------------------------------


def test_metrics_merge_relabels_per_replica():
    fleet, stubs = _stub_fleet(2)
    try:
        router = FleetRouter(fleet)
        router.submit([1], 2)
        text = router.metrics_text()
        # fleet/router series present once
        assert "# TYPE fleet_replica_state gauge" in text
        # per-replica stub series re-labelled the MetricsAggregator way
        assert 'stub_up{replica="0"} 1' in text
        assert 'stub_up{replica="1"} 1' in text
        # parseable as one exposition
        from tensorflowonspark_tpu.obs.cluster import (
            parse_prometheus_text,
        )

        parse_prometheus_text(text)
    finally:
        fleet.close()


def test_merge_families_exported_label_convention():
    from tensorflowonspark_tpu.obs.cluster import (
        merge_families,
        parse_prometheus_text,
    )

    text = '# TYPE x gauge\nx{replica="inner"} 5\n'
    merged = merge_families(
        {"0": parse_prometheus_text(text)}, label="replica"
    )
    assert 'exported_replica="inner"' in merged
    assert 'replica="0"' in merged


# -- real engines ------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny():
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models.llama import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(dtype=jnp.float32, remat=False)
    model = Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, model, params


def test_fleet_completions_match_single_engine(tiny):
    """Routing must not change results: a fleet-served completion is
    byte-identical to the single engine's (greedy, same params)."""
    import jax.numpy as jnp
    import numpy as np

    from tensorflowonspark_tpu.models.llama import generate
    from tensorflowonspark_tpu.serving import ContinuousBatcher

    cfg, model, params = tiny

    def factory():
        return ContinuousBatcher(
            model, params, slots=2, prompt_widths=(8,)
        )

    fleet = ServingFleet(
        factory=factory, replicas=2, probe_interval=0.2, warmup=False,
        drain_timeout=5.0,
    )
    try:
        router = FleetRouter(fleet)
        for p in ([1, 2, 3], [7, 5], [9, 9, 9, 4]):
            got = router.submit(p, 5)
            want = np.asarray(
                generate(model, params, jnp.asarray([p], jnp.int32), 5)
            )[0].tolist()
            assert got == want, (p, got, want)
        # streamed tokens too
        s = router.stream([3, 1], 4)
        toks = list(s)
        want = np.asarray(
            generate(model, params, jnp.asarray([[3, 1]], jnp.int32), 4)
        )[0].tolist()
        assert toks == want and s.result == want
    finally:
        router.close()


def test_fleet_prefix_warmth_reaches_replica_prefix_store(tiny):
    """Affinity routes the extension to the replica whose engine-side
    _PrefixStore is warm: its prefix_hits counter moves."""
    from tensorflowonspark_tpu.serving import ContinuousBatcher

    cfg, model, params = tiny

    def factory():
        return ContinuousBatcher(
            model, params, slots=2, prompt_widths=(8,),
            prefill_chunk=4, prefix_cache=4,
        )

    fleet = ServingFleet(
        factory=factory, replicas=2, probe_interval=0.2, warmup=False,
        drain_timeout=5.0,
    )
    try:
        router = FleetRouter(fleet)
        base = [5, 6, 7, 8, 9, 10]
        router.submit(base, 2)
        router.submit(base + [11, 12], 2)
        hits = []
        for v in fleet.views():
            st = v["handle"].stats()
            hits.append(st.get("prefix_hits", 0))
        assert sum(hits) >= 1, hits
        assert router.stats()["router"]["affinity_hits"] >= 1
    finally:
        router.close()


def test_engine_health_split(tiny):
    from tensorflowonspark_tpu.serving import ContinuousBatcher

    cfg, model, params = tiny
    eng = ContinuousBatcher(model, params, slots=1, prompt_widths=(8,))
    try:
        h = eng.health()
        assert h == {
            "live": True, "ready": True, "warming": False,
            "closed": False, "weights_version": "v0",
        }
        eng._warming = True
        assert eng.health()["ready"] is False
        assert eng.health()["live"] is True
        eng._warming = False
        assert eng.unresolved() == 0
        eng.submit([1], 2)
        assert eng.unresolved() == 0
    finally:
        eng.close()
    h = eng.health()
    assert h["ready"] is False  # closed engines are not routable


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _post(url, payload):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read().decode()), dict(
                r.headers
            )
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode()), dict(e.headers)


def test_serve_model_fleet_healthz_split_and_shed(tiny, tmp_path):
    """serve_model --gen-replicas 2 end to end: router-backed
    /generate, /healthz liveness vs /readyz readiness (per-replica +
    aggregated), fleet /stats, merged /metrics, 429 deadline shed with
    Retry-After, 503 during full-fleet drain."""
    import jax.numpy as jnp
    import numpy as np

    from tensorflowonspark_tpu.compute.checkpoint import (
        CheckpointManager,
    )
    from tensorflowonspark_tpu.models.llama import generate
    from tensorflowonspark_tpu.tools import serve_model

    cfg, model, params = tiny
    ckpt = str(tmp_path / "ckpt")
    with CheckpointManager(ckpt, async_save=False) as mgr:
        mgr.save(0, {"params": params})

    server = serve_model.make_server(
        None,
        port=0,
        gen=dict(
            checkpoint=ckpt,
            model="tiny",
            width=8,
            max_new_tokens=16,
            engine="continuous",
            slots=2,
            replicas=2,
            probe_interval=0.2,
        ),
    )
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    try:
        code, h = _get(base + "/healthz")
        assert code == 200 and h["live"] is True and h["ready"] is True
        assert set(h["replicas"]) == {"0", "1"}
        assert all(
            r["state"] == "ready" for r in h["replicas"].values()
        )
        code, r = _get(base + "/readyz")
        assert code == 200 and r["ready"] is True

        code, st = _get(base + "/stats")
        assert code == 200 and st["mode"] == "fleet"
        assert st["fleet"]["replicas"] == 2

        # router-backed /generate matches the reference
        code, out, _hdr = _post(
            base + "/generate", {"prompts": [[1, 2, 3]]}
        )
        want = np.asarray(
            generate(
                model, params, jnp.asarray([[1, 2, 3]], jnp.int32), 16
            )
        )[0].tolist()
        assert code == 200 and out["completions"][0] == want

        # merged /metrics carries per-replica engine series
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            text = r.read().decode()
        assert 'engine_requests_total{replica="' in text
        assert "router_requests_total" in text

        # deadline shed -> 429 + Retry-After (hint the service time;
        # the learned EWMA would beat the hint, so drop it)
        server.gen_engine._service_time_hint = 60.0
        with server.gen_engine._lock:
            server.gen_engine._est_req_s.clear()
        code, body, hdr = _post(
            base + "/generate",
            {"prompts": [[1, 2]], "deadline_s": 1.0},
        )
        assert code == 429, body
        assert body["error_type"] == "FleetOverloaded"
        assert int(hdr.get("Retry-After", "0")) >= 1
        server.gen_engine._service_time_hint = None

        # full-fleet drain: readyz flips 503, generate sheds 503
        server.gen_engine.begin_drain()
        code, r = _get(base + "/readyz")
        assert code == 503 and r["ready"] is False and r["live"] is True
        code, body, _hdr = _post(
            base + "/generate", {"prompts": [[1]]}
        )
        assert code == 503 and body["error_type"] == "FleetUnavailable"
    finally:
        server.shutdown()


# -- chaos e2e (slow) --------------------------------------------------------


def _tiny_ckpt_for_subprocess(tmp_path, tiny):
    from tensorflowonspark_tpu.compute.checkpoint import (
        CheckpointManager,
    )

    cfg, model, params = tiny
    ckpt = str(tmp_path / "ckpt")
    with CheckpointManager(ckpt, async_save=False) as mgr:
        mgr.save(0, {"params": params})
    return ckpt


@pytest.mark.slow
def test_fleet_sigkill_replica_under_streaming_load(tiny, tmp_path):
    """SIGKILL one of 2 subprocess replicas mid-stream: every in-flight
    request resolves as exactly one failover result or one terminal
    error (zero silent drops), the router flips the replica to
    DRAINING within the probe interval, the respawned replica passes
    readiness and serves again — all visible in flightrec and
    router_failover_total/fleet_respawns_total."""
    from tensorflowonspark_tpu.obs import flightrec

    ckpt = _tiny_ckpt_for_subprocess(tmp_path, tiny)
    rec_path = str(tmp_path / "flightrec-fleet.json")
    rec = flightrec.install(rec_path, process="fleet-test")
    argv = [
        "--llama-checkpoint", ckpt, "--model", "tiny",
        "--gen-engine", "continuous", "--gen-width", "8",
        "--max-new-tokens", "64", "--gen-slots", "4", "--gen-warmup",
    ]
    # throwaway compile cache for the SIGKILL-able children: a killed
    # process must never share a persistent compile cache others read
    # (a kill mid-write can tear an entry; see tests/test_rollout.py's
    # SIGKILL e2e and tests/conftest.py for the full note)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        JAX_COMPILATION_CACHE_DIR=str(tmp_path / "child-jax-cache"),
    )
    fleet = ServingFleet(
        spawn_argv=argv,
        replicas=2,
        probe_interval=0.5,
        drain_timeout=10.0,
        spawn_kwargs={"env": env, "spawn_timeout": 300.0},
    )
    router = FleetRouter(fleet)
    results: dict[int, object] = {}
    N = 8

    def one(i):
        try:
            s = router.stream([1 + i, 2, 3], 24)
            toks = list(s)
            results[i] = ("ok", toks)
        except BaseException as e:  # noqa: BLE001 - the verdict
            results[i] = ("err", e)

    try:
        threads = [
            threading.Thread(target=one, args=(i,), daemon=True)
            for i in range(N)
        ]
        for t in threads:
            t.start()
        # let streams open and start yielding, then SIGKILL a replica
        time.sleep(2.0)
        victim = None
        for v in fleet.views():
            if v["state"] == READY:
                victim = v
                break
        assert victim is not None
        os.kill(victim["handle"].pid, 9)
        t_kill = time.monotonic()

        # fresh submits racing the probe: one that routes to the dead
        # replica fails over invisibly; all must resolve either way
        post_kill: dict[int, object] = {}

        def submit_one(i):
            try:
                post_kill[i] = ("ok", router.submit([40 + i], 4))
            except BaseException as e:  # noqa: BLE001 - the verdict
                post_kill[i] = ("err", e)

        burst = [
            threading.Thread(target=submit_one, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in burst:
            t.start()

        # DRAINING within the probe window (+ grace for the flip)
        deadline = t_kill + 15.0
        seen_drain = False
        while time.monotonic() < deadline:
            if fleet.states()[victim["rid"]] in (DRAINING, STARTING):
                seen_drain = True
                break
            time.sleep(0.1)
        assert seen_drain, fleet.states()

        # ZERO silent drops: every request resolves (bounded join)
        for t in threads:
            t.join(timeout=180.0)
            assert not t.is_alive(), "a request hung — silent drop"
        assert set(results) == set(range(N))
        oks = [r for r in results.values() if r[0] == "ok"]
        errs = [r for r in results.values() if r[0] == "err"]
        # mid-stream kills are terminal errors; everything else
        # completed (possibly via failover)
        for kind, payload in results.values():
            if kind == "ok":
                assert payload, "empty completion"
            else:
                assert isinstance(
                    payload,
                    (ReplicaGone, EngineWedged, DeadlineExceeded),
                ), payload
        assert oks, results  # the fleet kept serving

        # respawn passes readiness and serves again
        deadline = time.monotonic() + 300.0
        while time.monotonic() < deadline:
            if all(s == READY for s in fleet.states().values()):
                break
            time.sleep(1.0)
        assert all(s == READY for s in fleet.states().values())
        assert router.submit([9, 8], 4)  # the respawned fleet serves

        for t in burst:
            t.join(timeout=120.0)
            assert not t.is_alive(), "post-kill submit hung"
        for kind, payload in post_kill.values():
            # a submit that raced the dead replica failed over
            # invisibly (no token had been consumed) — every one
            # resolves ok unless the failover pool itself was empty
            assert kind == "ok", payload

        st = router.stats()
        assert st["fleet"]["seats"][str(victim["rid"])]["respawns"] >= 1
        text = router.metrics_text()
        assert "fleet_respawns_total" in text
        assert "router_failover_total" in text
        # flightrec: drain + respawn events on the record
        kinds = [e["kind"] for e in rec.snapshot("test")["events"]]
        assert "replica_drain" in kinds
        assert "replica_respawn" in kinds
    finally:
        router.close()
        rec.stop()
        with flightrec._install_lock:
            flightrec._recorder = None


@pytest.mark.slow
def test_fleet_overload_shedding_bounds_admitted_p99(tiny):
    """2x sustained overload with shedding on: rejected requests get a
    FleetOverloaded/FleetUnavailable (429/503 class — never a hang)
    and the p99 latency of ADMITTED requests stays within the
    deadline budget."""
    from tensorflowonspark_tpu.serving import ContinuousBatcher

    cfg, model, params = tiny

    def factory():
        return ContinuousBatcher(
            model, params, slots=1, prompt_widths=(8,),
            max_queue=2, decode_block=2,
        )

    fleet = ServingFleet(
        factory=factory, replicas=2, probe_interval=0.2,
        warmup=True, drain_timeout=10.0,
    )
    router = FleetRouter(fleet, ewma_alpha=0.4)
    results = []
    res_lock = threading.Lock()
    deadline_box = [60.0]

    def one(i):
        t0 = time.monotonic()
        try:
            out = router.submit(
                [1 + (i % 5), 2], 24, deadline_s=deadline_box[0]
            )
            dur = time.monotonic() - t0
            with res_lock:
                results.append(("ok", dur, out))
        except (FleetOverloaded, FleetUnavailable) as e:
            with res_lock:
                results.append(("shed", time.monotonic() - t0, e))
        except DeadlineExceeded as e:
            with res_lock:
                results.append(("deadline", time.monotonic() - t0, e))
        except BaseException as e:  # noqa: BLE001 - the verdict
            with res_lock:
                results.append(("err", time.monotonic() - t0, e))

    try:
        # prime the EWMA + measure the unloaded service time; the
        # deadline budget is a small multiple of it so sustained
        # overload MUST shed (steady-state wait exceeds it)
        t0 = time.monotonic()
        router.submit([1, 2], 24)
        base_dur = time.monotonic() - t0
        DEADLINE = deadline_box[0] = max(1.0, 3.0 * base_dur)
        # sustained overload: 2 engine slots total, 10 concurrent
        # submitters re-firing for a sustained window
        stop_at = time.monotonic() + 15.0
        threads = []
        while time.monotonic() < stop_at:
            alive = [t for t in threads if t.is_alive()]
            while len(alive) < 10:
                t = threading.Thread(
                    target=one, args=(len(threads),), daemon=True
                )
                t.start()
                threads.append(t)
                alive.append(t)
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=120.0)
            assert not t.is_alive(), "a request hung under overload"
        kinds = [k for k, _, _ in results]
        assert "ok" in kinds
        oks = sorted(d for k, d, _ in results if k == "ok")
        # every admitted-and-completed request inside the budget at
        # p99 (the engine's own deadline enforcement backstops the
        # router's admission estimate)
        p99 = oks[min(len(oks) - 1, int(0.99 * len(oks)))]
        assert p99 <= DEADLINE + 2.0, (p99, len(oks))
        errs = [e for k, _, e in results if k == "err"]
        assert not errs, errs[:3]
        st = router.stats()["router"]
        # shedding engaged under 2x overload (deadline or queue_full)
        assert kinds.count("shed") + kinds.count("deadline") > 0, (
            st,
            kinds,
        )
    finally:
        router.close()
