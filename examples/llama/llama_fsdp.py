"""Llama fine-tune with FSDP over the ICI mesh — the BASELINE.md headline.

Reference parity: there is no reference equivalent (TFoS topped out at
data-parallel, SURVEY.md §2.3); this is the config BASELINE.json adds:
"Llama-2-7B fine-tune, FSDP over ICI, v4-32, ≥40% MFU". The same script
scales from a tiny CPU smoke run to the real thing by flags: mesh axes,
model size, remat, and checkpoint/resume are all config.

MFU accounting: 6*P*T model flops per token (fwd+bwd) over the measured
step time, against per-chip peak (float from --peak-tflops; v4 bf16 = 275).

Usage::

    tpu-submit --num-executors 1 examples/llama/llama_fsdp.py \
        [--model tiny|7b] [--fsdp -1] [--tp 1] [--steps 20] \
        [--seq 512] [--batch-size 8] [--model-dir DIR] [--cpu]
"""

from __future__ import annotations

import os as _os, sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import argparse
import time


def _config(name: str, seq: int):
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models.llama import LlamaConfig

    if name == "7b":
        return LlamaConfig(
            hidden_size=4096,
            intermediate_size=11008,
            num_layers=32,
            num_heads=32,
            num_kv_heads=32,
            vocab_size=32000,
            max_seq_len=seq,
            dtype=jnp.bfloat16,
            remat=True,
        )
    return LlamaConfig.tiny(
        hidden_size=256,
        intermediate_size=512,
        num_layers=4,
        num_heads=8,
        num_kv_heads=4,
        vocab_size=1024,
        max_seq_len=seq,
        dtype=jnp.bfloat16,
        remat=True,
    )


def main_fun(args, ctx):
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import (
        TrainState,
        build_train_step,
        shard_state,
    )
    from tensorflowonspark_tpu.compute.checkpoint import (
        CheckpointManager,
        chief_final_save,
        restore_latest,
        saves_on_this_process,
    )
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch
    from tensorflowonspark_tpu.models.llama import (
        Llama,
        llama_loss_fn,
        llama_param_shardings,
    )
    from tensorflowonspark_tpu.parallel import use_mesh

    cfg = _config(args.model, args.seq)
    if args.remat != "full":
        cfg = dataclasses.replace(
            cfg, remat=args.remat != "none", remat_policy=args.remat
        )
    if args.attention != "auto":
        cfg = dataclasses.replace(cfg, attention_impl=args.attention)
    if args.sp > 1:
        # Sequence parallelism: 'ring' rotates KV blocks around the ring
        # (memory-optimal for long S_local); 'ulysses' does two
        # all-to-alls and runs full-sequence attention per head subset
        # (fewer collectives; needs heads divisible by sp).
        cfg = dataclasses.replace(cfg, attention_impl=args.sp_impl)
    model = Llama(cfg)
    mesh = make_mesh(
        {"data": args.dp, "fsdp": args.fsdp, "model": args.tp, "seq": args.sp}
    )
    if ctx.executor_id == 0:
        print(f"mesh: {dict(mesh.shape)}")

    rng = np.random.default_rng(ctx.executor_id)
    # The SP shard_maps need the init batch to divide over (data, fsdp);
    # other impls keep the cheap batch-2 init.
    dp_size = mesh.shape["data"] * mesh.shape["fsdp"]
    init_b = dp_size if cfg.attention_impl in ("ring", "ulysses") else 2
    tokens0 = np.zeros((init_b, args.seq + 1), np.int32)
    with use_mesh(mesh):
        params = model.init(jax.random.PRNGKey(0), tokens0[:, :-1])["params"]
    if args.lora_rank:
        from tensorflowonspark_tpu.ops.lora import add_lora

        # parameter-efficient fine-tune: only rank-r adapters train;
        # the frozen base carries no gradients and no optimizer moments
        params = add_lora(
            params, rank=int(args.lora_rank), rng=jax.random.PRNGKey(1)
        )
    psh = llama_param_shardings(params, mesh)
    params = jax.tree.map(jax.device_put, params, psh)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))

    moment_dtype = jnp.bfloat16 if args.moments == "bf16" else None
    # standard large-model LR recipe: linear warmup -> cosine decay to
    # 10% of peak; --warmup 0 keeps the constant LR (every optimizer
    # here accepts a schedule callable)
    if args.warmup > 0:
        # The schedule indexes the RESTORED optimizer count on resume, so
        # its horizon must be the TOTAL run length across all legs —
        # --total-steps (kept identical on every resume invocation), not
        # this leg's --steps; otherwise a resumed leg would start past
        # the decay clamp and train entirely at end_value.
        total = args.total_steps or args.steps
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=float(args.lr),
            warmup_steps=args.warmup,
            decay_steps=max(total, args.warmup + 1),
            end_value=0.1 * float(args.lr),
        )
    else:
        lr = float(args.lr)
    if args.precision == "mixed":
        from tensorflowonspark_tpu.compute import mixed_precision_adamw

        # bf16 stored params + fp32 master in the optimizer state
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        tx = mixed_precision_adamw(lr, moment_dtype=moment_dtype)
    elif args.moments == "bf16":
        from tensorflowonspark_tpu.compute import optim

        tx = optim.adamw(lr, moment_dtype=jnp.bfloat16)
    else:
        tx = optax.adamw(lr)
    if args.clip > 0:
        # global-norm clip BEFORE the optimizer (the usual transformer
        # training guard against loss spikes)
        tx = optax.chain(optax.clip_by_global_norm(float(args.clip)), tx)
    if args.lora_rank:
        from tensorflowonspark_tpu.ops.lora import lora_optimizer

        # masks moments down to the adapters — the HBM win
        tx = lora_optimizer(tx, params)
    # commit ALL state leaves (moments, masters, step scalar) to their
    # mesh shardings — required for checkpoint restore to reproduce
    # placements exactly under multi-controller FSDP
    state = shard_state(TrainState.create(params, tx), mesh, psh)
    token_loss = llama_loss_fn(model, logit_chunk=args.logit_chunk)
    weight_fn = None
    if args.packed:
        from tensorflowonspark_tpu.models.llama import packed_valid_count

        loss_fn = lambda p, b: token_loss(  # noqa: E731
            p, b["tokens"], segment_ids=b["segment_ids"]
        )
        # exact token weighting under accumulation: packed microbatches
        # have data-dependent valid counts, so weight each by its count
        weight_fn = lambda b: packed_valid_count(b["segment_ids"])  # noqa: E731
    else:
        loss_fn = lambda p, b: token_loss(p, b["tokens"])  # noqa: E731
    step = build_train_step(
        loss_fn, tx, mesh, param_shardings=psh, accum_steps=args.accum,
        batch_weight_fn=weight_fn,
    )

    ckpt = None
    if args.model_dir:
        ckpt = CheckpointManager(
            ctx.absolute_path(args.model_dir),
            save_interval_steps=args.save_every or 1,
        )
        latest, restored = restore_latest(ckpt, state)
        if latest is not None:
            if ctx.is_chief:
                print(f"resuming from step {latest}")
            state = restored

    if args.packed:
        from tensorflowonspark_tpu.data.packing import pack_batches

        def synthetic_docs():
            # variable-length documents, the shape real corpora have
            lo = min(8, max(1, args.seq // 2))
            hi = max(lo + 1, args.seq)
            while True:
                n = int(rng.integers(lo, hi))
                yield rng.integers(1, cfg.vocab_size, size=n).tolist()

        packed_iter = pack_batches(
            synthetic_docs(), args.batch_size, args.seq
        )

        def batch():
            return next(packed_iter)

    else:

        def batch():
            return {
                "tokens": rng.integers(
                    0, cfg.vocab_size, size=(args.batch_size, args.seq + 1)
                ).astype(np.int32)
            }

    with use_mesh(mesh):
        # compile + warmup excluded from timing
        state, loss = step(state, shard_batch(mesh, batch()))
        jax.block_until_ready(loss)
        # host-side step counter: int(state.step) inside the loop would
        # force a device sync every iteration and kill async dispatch
        step_base = int(state.step)
        t0 = time.time()
        for i in range(args.steps):
            state, loss = step(state, shard_batch(mesh, batch()))
            if (i + 1) % 10 == 0:
                print(
                    f"node{ctx.executor_id} step {i + 1} "
                    f"loss {float(loss):.4f}"
                )
            if (
                ckpt is not None
                and args.save_every
                and saves_on_this_process(ctx.is_chief)
            ):
                # async save overlapped with the next steps; the manager's
                # save_interval policy decides which steps actually land.
                # Under multi-controller FSDP the state is sharded across
                # processes, so EVERY process participates in the save.
                ckpt.save(step_base + 1 + i, state)
        jax.block_until_ready(loss)
    dt = time.time() - t0

    step_time = dt / args.steps
    tokens_per_step = args.batch_size * args.seq
    model_flops = 6 * n_params * tokens_per_step  # fwd+bwd, no attn term
    mfu = model_flops / step_time / jax.device_count() / (
        args.peak_tflops * 1e12
    )
    print(
        f"node{ctx.executor_id}: {n_params / 1e6:.1f}M params, "
        f"step {step_time * 1e3:.1f}ms, "
        f"{tokens_per_step / step_time:.0f} tokens/sec "
        f"({tokens_per_step / step_time / jax.device_count():.0f} /chip), "
        f"MFU {mfu * 100:.1f}%"
    )
    if ckpt is not None:
        # Single-controller: chief-only (independent replicas would race
        # on the directory). Multi-controller: collective all-process save
        # of the cross-process-sharded state. chief_final_save picks.
        chief_final_save(ckpt, state, int(state.step), ctx.is_chief)
        if ctx.is_chief:
            print(f"checkpointed step {int(state.step)} to {args.model_dir}")

    if args.generate:
        from tensorflowonspark_tpu.models.llama import generate

        # SPMD: every process runs the same decode over the (possibly
        # globally sharded) params; only the chief prints. A device_get of
        # FSDP-sharded params would fail multi-host — keep them on-mesh.
        gen_params = state.params
        if args.lora_rank:
            from tensorflowonspark_tpu.ops.lora import merge_lora

            # fold adapters into plain kernels: zero decode overhead,
            # and quantize_tree below would otherwise descend INTO the
            # LoraTensor and quantize its base out from under lora_apply
            with use_mesh(mesh):
                gen_params = jax.jit(merge_lora)(gen_params)
        if args.quantize_decode:
            from tensorflowonspark_tpu.ops.quant import (
                QuantTensor,
                quantize_tree,
            )

            # int8 weight-only decode (ops/quant.py): the model consumes
            # the quantized tree natively (QDense/quantized_dot), so
            # weights stay int8 through the decode. jit so quantization
            # runs as SPMD on FSDP-sharded (non-fully-addressable) params
            # instead of eagerly; drop the bf16 state so its buffers can
            # actually be freed.
            with use_mesh(mesh):
                gen_params = jax.jit(quantize_tree)(gen_params)
            state = None
            n_q = sum(
                isinstance(leaf, QuantTensor)
                for leaf in jax.tree.leaves(
                    gen_params, is_leaf=lambda x: isinstance(x, QuantTensor)
                )
            )
            if ctx.is_chief:
                print(
                    f"quantized {n_q} weight tensors for decode"
                    + (
                        " (NONE met quantize_tree's size threshold — "
                        "tiny configs decode unquantized)"
                        if n_q == 0
                        else ""
                    )
                )
        gen_rng = np.random.default_rng(0)  # same prompt on every process
        prompt = gen_rng.integers(
            0, cfg.vocab_size, size=(2, 8)
        ).astype(np.int32)
        t0 = time.time()
        with use_mesh(mesh):
            out = generate(
                model,
                gen_params,
                jax.numpy.asarray(prompt),
                max_new_tokens=args.generate,
                temperature=args.temperature,
                top_k=args.top_k,
                top_p=args.top_p,
                eos_id=args.eos_id,
            )
        jax.block_until_ready(out)
        dt = time.time() - t0
        if ctx.is_chief:
            out_np = np.asarray(out)
            if args.eos_id is None:
                n_generated = float(args.generate)
            else:
                # count tokens up to and including each row's first EOS;
                # the eos-padded tail was never decoded (early stop)
                hit = out_np == args.eos_id
                first = np.where(
                    hit.any(axis=1), hit.argmax(axis=1) + 1, out_np.shape[1]
                )
                n_generated = float(first.mean())
            print(
                f"generated {n_generated:.1f} tokens/seq (KV-cache "
                f"decode) in {dt:.1f}s: {out_np[0][:10].tolist()}"
            )


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=("tiny", "7b"), default="tiny")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--fsdp", type=int, default=-1, help="-1: all devices")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument(
        "--sp", type=int, default=1,
        help="sequence-parallel axis size",
    )
    p.add_argument(
        "--sp-impl", choices=("ring", "ulysses"), default="ring",
        help="sequence-parallel strategy",
    )
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument(
        "--warmup",
        type=int,
        default=0,
        help="linear-warmup steps into a cosine decay (0: constant LR)",
    )
    p.add_argument(
        "--total-steps",
        type=int,
        default=0,
        help="cosine-decay horizon across ALL resume legs (0: this "
        "invocation's --steps); keep identical when resuming so the "
        "restored optimizer count lands on a coherent schedule",
    )
    p.add_argument(
        "--clip",
        type=float,
        default=0.0,
        help="global-norm gradient clip (0: off)",
    )
    p.add_argument(
        "--precision",
        choices=("fp32", "mixed"),
        default="fp32",
        help="mixed: bf16 stored params + fp32 master (compute/optim.py)",
    )
    p.add_argument(
        "--moments",
        choices=("fp32", "bf16"),
        default="bf16",
        help="Adam moment storage dtype (bf16 frees 4 bytes/param of HBM)",
    )
    p.add_argument(
        "--accum",
        type=int,
        default=1,
        help="gradient-accumulation microbatches per optimizer step "
        "(batch-size must divide evenly); the HBM lever when the target "
        "global batch's activations exceed memory even after remat",
    )
    p.add_argument(
        "--packed",
        action="store_true",
        help="pack variable-length synthetic documents into each row "
        "(data/packing.py); trains with per-document attention "
        "isolation + boundary/padding loss masking",
    )
    p.add_argument(
        "--logit-chunk",
        type=int,
        default=None,
        help="chunked-CE chunk length; skips the (B,S,V) fp32 logits",
    )
    p.add_argument(
        "--lora-rank",
        type=int,
        default=0,
        help="parameter-efficient fine-tune: wrap attention/MLP kernels "
        "in rank-R LoRA adapters (ops/lora.py) — only adapters train, "
        "the frozen base carries no grads and no optimizer moments "
        "(0 = full fine-tune)",
    )
    p.add_argument("--model-dir", default=None)
    p.add_argument(
        "--save-every",
        type=int,
        default=0,
        help="mid-training checkpoint interval in steps (0: only at end)",
    )
    p.add_argument(
        "--generate",
        type=int,
        default=0,
        help="after training, decode N tokens via the KV cache (chief)",
    )
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument(
        "--eos-id",
        type=int,
        default=None,
        help="stop each row at this token (decode exits early once all "
        "rows finish)",
    )
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument(
        "--quantize-decode",
        action="store_true",
        help="int8 weight-only storage for the --generate decode pass",
    )
    p.add_argument(
        "--peak-tflops", type=float, default=275.0, help="per-chip bf16 peak"
    )
    p.add_argument(
        "--remat", choices=("full", "dots", "none"), default="full",
        help="rematerialization policy (none = keep activations)",
    )
    p.add_argument(
        "--attention", choices=("auto", "xla", "flash"), default="auto",
        help="attention impl when not sequence-parallel",
    )
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args(argv)
    if (args.top_k is not None or args.top_p is not None) and (
        args.temperature == 0.0
    ):
        # fail at parse time, not after the whole training run
        p.error("--top-k/--top-p require --temperature > 0")
    return args


if __name__ == "__main__":
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()
    cluster = tfcluster.run(
        main_fun,
        args,
        num_executors=largs["num_executors"],
        input_mode=InputMode.TENSORFLOW,
        env=cpu_only_env() if args.cpu else None,
        launcher=largs.get("launcher"),
        distributed=largs.get("distributed", False),
    )
    cluster.shutdown()
    print("llama_fsdp done")
