"""U-Net semantic segmentation, InputMode.TENSORFLOW.

Reference parity: ``examples/segmentation`` (TF2 port of the TF
image-segmentation tutorial: U-Net on Oxford-IIIT Pet, nodes read their own
data — SURVEY.md §2.4). Synthetic stand-in data: random circles rendered
into images, mask = {0: background, 1: disk, 2: outline}, so the model has
real structure to learn and mIoU is a meaningful metric.

Usage::

    tpu-submit --num-executors 1 examples/segmentation/unet_segmentation.py \
        [--steps 100] [--size 64] [--tiny] [--cpu] [--model-dir DIR]
"""

from __future__ import annotations

import os as _os, sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import argparse
import time


def _render_circles(rng, n, size):
    """(images, masks): anti-aliased disks with distinct outline class."""
    import numpy as np

    yy, xx = np.mgrid[0:size, 0:size]
    images = np.zeros((n, size, size, 3), np.float32)
    masks = np.zeros((n, size, size), np.int32)
    for i in range(n):
        cx, cy = rng.uniform(size * 0.25, size * 0.75, size=2)
        r = rng.uniform(size * 0.1, size * 0.3)
        d = np.sqrt((xx - cx) ** 2 + (yy - cy) ** 2)
        disk = d < r - 1.5
        outline = (d >= r - 1.5) & (d < r + 1.5)
        masks[i][disk] = 1
        masks[i][outline] = 2
        color = rng.uniform(0.3, 1.0, size=3).astype(np.float32)
        images[i][disk] = color
        images[i][outline] = 1.0 - color
        images[i] += rng.normal(0, 0.05, size=(size, size, 3))
    return images, masks


def main_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch
    from tensorflowonspark_tpu.models import unet

    cfg = unet.UNetConfig.tiny() if args.tiny else unet.UNetConfig()
    model = unet.UNet(cfg)
    mesh = make_mesh()
    rng = np.random.default_rng(ctx.executor_id)

    params = model.init(
        jax.random.PRNGKey(0),
        np.zeros((2, args.size, args.size, 3), np.float32),
    )["params"]
    psh = unet.unet_param_shardings(params, mesh)
    params = jax.tree.map(jax.device_put, params, psh)
    tx = optax.adam(1e-3)
    state = TrainState.create(params, tx)
    step = build_train_step(unet.loss_fn(model), tx, mesh, param_shardings=psh)

    t0 = time.time()
    loss = None
    for i in range(args.steps):
        images, masks = _render_circles(rng, args.batch_size, args.size)
        state, loss = step(
            state, shard_batch(mesh, {"image": images, "mask": masks})
        )
        if (i + 1) % 20 == 0:
            print(
                f"node{ctx.executor_id} step {i + 1} loss {float(loss):.4f}"
            )
    jax.block_until_ready(loss)
    dt = time.time() - t0

    images, masks = _render_circles(rng, args.batch_size, args.size)
    miou = unet.iou(
        model,
        jax.device_get(state.params),
        {"image": images, "mask": masks},
        cfg.num_classes,
    )
    print(
        f"node{ctx.executor_id}: {args.steps} steps in {dt:.1f}s, "
        f"final loss {float(loss):.4f}, mIoU {float(miou):.3f}"
    )
    if args.model_dir and ctx.is_chief:
        ctx.export_saved_model(jax.device_get(state.params), args.model_dir)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--size", type=int, default=64)
    p.add_argument("--model-dir", default=None)
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--cpu", action="store_true")
    return p.parse_args(argv)


if __name__ == "__main__":
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()
    cluster = tfcluster.run(
        main_fun,
        args,
        num_executors=largs["num_executors"],
        input_mode=InputMode.TENSORFLOW,
        env=cpu_only_env() if args.cpu else None,
        launcher=largs.get("launcher"),
        distributed=largs.get("distributed", False),
    )
    cluster.shutdown()
    print("unet_segmentation done")
