"""Online LLM serving with continuous batching — end to end.

No reference counterpart: the reference's serving story stopped at batch
scoring over partitions (SURVEY.md §2.2); this demonstrates the
rebuild's beyond-reference online path. The script

1. creates (or reuses) a tiny Llama MULTI-LORA BANK checkpoint (one
   base model + two fake-trained adapters),
2. starts `tools/serve_model` in-process with `--gen-engine continuous`,
3. fires concurrent /generate requests — mixed greedy/sampled
   temperatures, per-request budgets, per-request LoRA adapters — that
   share the engine's slots,
4. streams one completion token-by-token (NDJSON `stream: true`),
5. prints /stats (slot occupancy, TTFT and latency averages, prefix
   cache and adapter counters).

Run (CPU, ~1 min, most of it XLA compiles)::

    python examples/serving/serve_continuous.py [--slots 4]

On a TPU pod, point it at a real checkpoint and add
``--gen-mesh model=4`` for TP serving; everything else is identical.
"""

import argparse
import json
import os as _os
import sys
import threading
import time
import urllib.request

sys.path.insert(
    0,
    _os.path.abspath(
        _os.path.join(_os.path.dirname(__file__), "..", "..")
    ),
)


def ensure_checkpoint(path: str) -> None:
    """A base model + two 'fine-tuned' adapters stacked into one served
    bank (slot 0 is always the exact base; slots 1-2 the adapters)."""
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.compute import TrainState
    from tensorflowonspark_tpu.compute.checkpoint import CheckpointManager
    from tensorflowonspark_tpu.models.llama import Llama, LlamaConfig
    from tensorflowonspark_tpu.ops import lora

    with CheckpointManager(path, async_save=False) as mgr:
        if mgr.latest_step() is not None:
            return  # reuse the demo checkpoint from a previous run
        cfg = LlamaConfig.tiny(dtype=jnp.float32, remat=False)
        model = Llama(cfg)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]

        def fake_finetune(seed):
            tree = lora.add_lora(
                params, rank=4, rng=jax.random.PRNGKey(seed)
            )
            keys = iter(jax.random.split(jax.random.PRNGKey(seed), 999))
            return jax.tree.map(
                lambda x: lora.LoraTensor(
                    base=x.base, a=x.a,
                    b=0.02 * jax.random.normal(
                        next(keys), x.b.shape, x.b.dtype
                    ),
                    scale=x.scale,
                )
                if isinstance(x, lora.LoraTensor)
                else x,
                tree,
                is_leaf=lambda x: isinstance(x, lora.LoraTensor),
            )

        bank = lora.multi_lora_bank(
            [fake_finetune(1), fake_finetune(2)]
        )
        state = TrainState.create(bank, optax.sgd(0.1))
        mgr.save(0, state, force=True)


def post(port: int, payload: dict) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--checkpoint", default="/tmp/serving_demo_bank_ckpt")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--gen-mesh", default=None)
    args = ap.parse_args()

    ensure_checkpoint(args.checkpoint)

    from tensorflowonspark_tpu.tools import serve_model

    argv = [
        "--llama-checkpoint", args.checkpoint,
        "--model", "tiny",
        "--config-overrides", '{"remat": false, "dtype": "float32"}',
        "--gen-width", "16",
        "--max-new-tokens", "12",
        "--gen-engine", "continuous",
        "--gen-slots", str(args.slots),
        "--gen-prefill-chunk", "8",  # long admissions interleave
        "--gen-prefix-cache", "8",  # shared prefixes resume, not recompute
        "--port", "0",
    ]
    if args.gen_mesh:
        argv += ["--gen-mesh", args.gen_mesh]
    server_thread = threading.Thread(
        target=serve_model.main, args=(argv,), daemon=True
    )
    server_thread.start()
    while serve_model._last_server is None:
        if not server_thread.is_alive():
            print("server failed to start (see traceback above)")
            return 1
        time.sleep(0.2)
    server = serve_model._last_server
    port = server.server_address[1]
    print(f"serving on :{port} with {args.slots} slots")

    # concurrent requests: greedy and sampled share the decode loop
    payloads = [
        {"prompts": [[1, 2, 3]], "temperature": 0.0},
        {"prompts": [[4, 5]], "temperature": 0.9, "max_new_tokens": 6},
        {"prompts": [[7, 8, 9, 10]], "temperature": 0.0,
         "max_new_tokens": 8},
        # same prompt as the first request, but routed through LoRA
        # adapter 1 — a different tenant's fine-tune on shared slots
        {"prompts": [[1, 2, 3]], "temperature": 0.0, "adapter": 1},
    ]
    results = [None] * len(payloads)
    threads = [
        threading.Thread(
            target=lambda i=i: results.__setitem__(
                i, post(port, payloads[i])
            )
        )
        for i in range(len(payloads))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for p, r in zip(payloads, results):
        if r is None:  # its thread's HTTP error went to stderr
            print(f"prompt={p['prompts'][0]} FAILED (see traceback)")
            return 1
        tag = f" adapter={p['adapter']}" if "adapter" in p else ""
        print(f"prompt={p['prompts'][0]} temp={p['temperature']}{tag} "
              f"-> {r['completions'][0]}")

    # reproducible sampling: a seeded request returns the same
    # completion on every submission, regardless of what else the
    # engine decoded in between (per-(seed, position) keys); top_p
    # truncates this row's nucleus without recompiling anything
    seeded = {
        "prompts": [[4, 5]], "temperature": 0.9, "top_p": 0.9,
        "seed": 42, "max_new_tokens": 6,
    }
    first = post(port, seeded)
    second = post(port, seeded)
    if first["completions"] != second["completions"]:
        print(f"seeded request NOT reproducible: {first} vs {second}")
        return 1
    print(f"seeded(42, top_p=0.9) -> {first['completions'][0]} (x2, "
          "identical)")

    # stream a completion token by token, with per-token logprobs
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(
            {"prompts": [[1, 2, 3]], "stream": True, "logprobs": True}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    print("streaming:", end=" ", flush=True)
    with urllib.request.urlopen(req) as r:
        for line in r:
            msg = json.loads(line)
            if "token" in msg:
                print(
                    f"{msg['token']}({msg['logprob']:.2f})",
                    end=" ",
                    flush=True,
                )
            elif msg.get("done"):
                print("(done)")
            elif "error" in msg:
                # mid-stream failure arrives as an error line (the 200
                # status is already on the wire) — fail the demo
                print(f"(stream failed: {msg['error']})")
                return 1

    with urllib.request.urlopen(f"http://127.0.0.1:{port}/stats") as r:
        print("stats:", json.dumps(json.loads(r.read()), indent=2))
    server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
