"""MNIST training with MANIFEST feeding — node-side feeders in SPARK mode.

The push plane routes every byte through the driver (measured ceiling:
BASELINE.md "Push-plane ceiling"); the reference never hit this because
its feed tasks ran on the executors with HDFS locality. This example
restores that property: the driver feeds ``FileManifest`` records (one
per TFRecord shard — O(files) driver bytes) and every node expands its
manifests locally through ``ManifestFeed``. Same cluster API, same
training loop shape as ``mnist_spark.py``.

Usage::

    python examples/mnist/mnist_data_setup.py --output /tmp/mnist_tfr
    tpu-submit --num-executors 2 examples/mnist/mnist_manifest.py \
        --tfrecords /tmp/mnist_tfr [--batch-size 256] [--cpu]
"""

from __future__ import annotations

import os as _os, sys as _sys

# examples are runnable without installing the package
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))


import argparse


def main_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh
    from tensorflowonspark_tpu.feed.manifest import ManifestFeed
    from tensorflowonspark_tpu.feed.prefetch import DevicePrefetcher
    from tensorflowonspark_tpu.models import mnist

    model = mnist.CNN()
    mesh = make_mesh()
    # the driver ships paths; this node reads its shard files locally
    feed = ManifestFeed(ctx.get_data_feed(train_mode=True))

    params = model.init(
        jax.random.PRNGKey(0), np.zeros((2, 28, 28, 1), np.float32)
    )["params"]
    tx = optax.adam(1e-3)
    state = TrainState.create(params, tx)
    step = build_train_step(mnist.loss_fn(model.apply), tx, mesh)

    def prepare(cols):
        n = len(cols["label"])
        return {
            "image": np.asarray(cols["image"], np.float32).reshape(
                n, 28, 28, 1
            )
            / 255.0,
            "label": np.asarray(cols["label"], np.int32),
        }

    steps = 0
    with DevicePrefetcher.from_feed(
        feed,
        args.batch_size,
        mesh,
        multiple_of=jax.device_count(),
        prepare=prepare,
        input_mapping={"image": "image", "label": "label"},
    ) as pf:
        for batch in pf:
            state, loss = step(state, batch)
            steps += 1
            if steps % 20 == 0:
                print(
                    f"node{ctx.executor_id} step {steps} loss {float(loss):.4f}"
                )
    print(f"node{ctx.executor_id} finished after {steps} steps")

    if args.model_dir and ctx.is_chief:
        ctx.export_saved_model(jax.device_get(state.params), args.model_dir)
        print(f"chief (node{ctx.executor_id}) exported to {args.model_dir}")


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--tfrecords", required=True, help="TFRecord dir (mnist_data_setup.py output)")
    p.add_argument("--model-dir", default=None)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--cpu", action="store_true", help="force CPU-only nodes")
    return p.parse_args(argv)


if __name__ == "__main__":
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.data import dfutil
    from tensorflowonspark_tpu.feed.manifest import FileManifest
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()

    # one manifest per TFRecord shard — the driver never touches the bytes
    manifests = [
        FileManifest(path) for path in dfutil.tfrecord_files(args.tfrecords)
    ]
    if not manifests:
        raise SystemExit(f"no TFRecord shards under {args.tfrecords}")
    n_exec = largs["num_executors"]
    partitions = [manifests[i::n_exec] for i in range(min(n_exec, len(manifests)))]

    cluster = tfcluster.run(
        main_fun,
        args,
        num_executors=n_exec,
        input_mode=InputMode.SPARK,
        env=cpu_only_env() if args.cpu else None,
        launcher=largs.get("launcher"),
        distributed=largs.get("distributed", False),
    )
    cluster.train(partitions, num_epochs=args.epochs, close_feed=True)
    cluster.shutdown()
    print("mnist_manifest done")
