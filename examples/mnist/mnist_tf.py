"""MNIST training, InputMode.TENSORFLOW — nodes read their own data.

Reference parity: ``examples/mnist/keras/mnist_tf.py`` (each worker read
its shard of the TFRecords directly; ``compat.disable_auto_shard`` kept TF
from re-sharding). Here each node reads records and takes its
``executor_id``-strided shard — per-host readers feeding the local mesh.

Usage::

    tpu-submit --num-executors 2 examples/mnist/mnist_tf.py \
        --tfrecords /tmp/mnist_tfr [--cpu]
"""

from __future__ import annotations

import os as _os, sys as _sys

# examples are runnable without installing the package
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))


import argparse


def main_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch
    from tensorflowonspark_tpu.data import readers
    from tensorflowonspark_tpu.models import mnist

    model = mnist.CNN()
    mesh = make_mesh()
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((2, 28, 28, 1), np.float32)
    )["params"]
    tx = optax.adam(1e-3)
    state = TrainState.create(params, tx)
    step = build_train_step(mnist.loss_fn(model.apply), tx, mesh)

    # Streaming per-node pipeline: shard -> shuffle -> repeat -> batch
    # (the tf.data role, InputMode.TENSORFLOW contract).
    def preprocess(b):
        return {
            "image": b["image"].astype(np.float32).reshape(-1, 28, 28, 1)
            / 255.0,
            "label": b["label"].astype(np.int32),
        }

    if args.pipeline == "tfdata":
        # the tf.data tier (data/tfdata.py): parallel interleaved reads,
        # parallel Example parsing, autotuned prefetch — file-sharded
        from tensorflowonspark_tpu.data.tfdata import tfdata_batches

        # same guard as the python tier's multiple_of: the batch must
        # split evenly over the mesh's data axis
        bs = max(
            jax.device_count(),
            args.batch_size // jax.device_count() * jax.device_count(),
        )
        batches = (
            preprocess(b)
            for b in tfdata_batches(
                args.tfrecords,
                bs,
                shard_index=ctx.executor_id,
                num_shards=ctx.num_workers,
                shuffle_buffer=4096,
                num_epochs=args.epochs,
                seed=ctx.executor_id,
            )
        )
    else:
        batches = readers.column_batches(
            readers.repeated(
                lambda epoch: readers.shuffled(
                    readers.sharded_rows(
                        args.tfrecords, ctx.executor_id, ctx.num_workers
                    ),
                    # fresh permutation each epoch, distinct per node
                    seed=ctx.executor_id * 10007 + epoch,
                ),
                epochs=args.epochs,
            ),
            args.batch_size,
            multiple_of=jax.device_count(),
            transform=preprocess,
        )
    steps, loss = 0, None
    for batch in batches:
        state, loss = step(state, shard_batch(mesh, batch))
        steps += 1
        if steps % 20 == 0:
            print(f"node{ctx.executor_id} step {steps} loss {float(loss):.4f}")
    if steps == 0:
        raise RuntimeError(
            f"node{ctx.executor_id}: shard too small for the "
            f"{jax.device_count()}-device mesh; nothing to train on"
        )
    print(f"node{ctx.executor_id}: {steps} steps, loss {float(loss):.4f}")

    if args.model_dir:
        ctx.export_saved_model(jax.device_get(state.params), args.model_dir)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--tfrecords", required=True)
    p.add_argument("--model-dir", default=None)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument(
        "--pipeline",
        choices=("python", "tfdata"),
        default="python",
        help="input tier: pure-Python readers or the tf.data adapter",
    )
    p.add_argument("--cpu", action="store_true")
    return p.parse_args(argv)


if __name__ == "__main__":
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()
    cluster = tfcluster.run(
        main_fun,
        args,
        num_executors=largs["num_executors"],
        input_mode=InputMode.TENSORFLOW,
        env=cpu_only_env() if args.cpu else None,
        launcher=largs.get("launcher"),
        distributed=largs.get("distributed", False),
    )
    cluster.shutdown()
    print("mnist_tf done")
