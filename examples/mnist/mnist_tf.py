"""MNIST training, InputMode.TENSORFLOW — nodes read their own data.

Reference parity: ``examples/mnist/keras/mnist_tf.py`` (each worker read
its shard of the TFRecords directly; ``compat.disable_auto_shard`` kept TF
from re-sharding). Here each node reads records and takes its
``executor_id``-strided shard — per-host readers feeding the local mesh.

Usage::

    tpu-submit --num-executors 2 examples/mnist/mnist_tf.py \
        --tfrecords /tmp/mnist_tfr [--cpu]
"""

from __future__ import annotations

import os as _os, sys as _sys

# examples are runnable without installing the package
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))


import argparse


def main_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch
    from tensorflowonspark_tpu.data import dfutil
    from tensorflowonspark_tpu.models import mnist

    # Per-node shard of the record files (InputMode.TENSORFLOW contract).
    rows = [
        r
        for i, r in enumerate(dfutil.loadTFRecords(args.tfrecords))
        if i % ctx.num_workers == ctx.executor_id
    ]
    images = (
        np.stack([np.asarray(r["image"], np.float32) for r in rows]).reshape(
            -1, 28, 28, 1
        )
        / 255.0
    )
    labels = np.asarray([int(r["label"]) for r in rows], np.int32)

    model = mnist.CNN()
    mesh = make_mesh()
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((2, 28, 28, 1), np.float32)
    )["params"]
    tx = optax.adam(1e-3)
    state = TrainState.create(params, tx)
    step = build_train_step(mnist.loss_fn(model.apply), tx, mesh)

    dc = jax.device_count()
    bs = args.batch_size - args.batch_size % dc
    if bs > len(labels):  # shard smaller than one batch: shrink, don't skip
        bs = len(labels) - len(labels) % dc
    if bs == 0:
        raise RuntimeError(
            f"node{ctx.executor_id}: shard of {len(labels)} records is "
            f"smaller than the {dc}-device mesh; nothing to train on"
        )
    steps = 0
    for epoch in range(args.epochs):
        for start in range(0, len(labels) - bs + 1, bs):
            batch = {
                "image": images[start : start + bs],
                "label": labels[start : start + bs],
            }
            state, loss = step(state, shard_batch(mesh, batch))
            steps += 1
        print(f"node{ctx.executor_id} epoch {epoch} loss {float(loss):.4f}")

    if args.model_dir:
        assert steps > 0  # never export random-init params
        ctx.export_saved_model(jax.device_get(state.params), args.model_dir)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--tfrecords", required=True)
    p.add_argument("--model-dir", default=None)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--cpu", action="store_true")
    return p.parse_args(argv)


if __name__ == "__main__":
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()
    cluster = tfcluster.run(
        main_fun,
        args,
        num_executors=largs["num_executors"],
        input_mode=InputMode.TENSORFLOW,
        env=cpu_only_env() if args.cpu else None,
        launcher=largs.get("launcher"),
        distributed=largs.get("distributed", False),
    )
    cluster.shutdown()
    print("mnist_tf done")
