"""MNIST training from a micro-batch stream — the Spark Streaming path.

Reference parity: ``TFCluster.train`` with a DStream (``foreachRDD`` fed
each RDD on arrival; SURVEY.md §3.2). Here a generator yields micro-batches
(simulating records arriving over time) into ``cluster.train_stream``;
workers consume through the same ``DataFeed``/``batch_stream`` surface as
batch training, and stop via ``DataFeed.terminate`` when they have seen
enough — which ``train_stream`` notices and returns early.

Usage::

    tpu-submit --num-executors 2 examples/mnist/mnist_streaming.py \
        [--micro-batches 20] [--interval 0.2] [--target-steps 30] [--cpu]
"""

from __future__ import annotations

import os as _os, sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import argparse
import time


def main_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh
    from tensorflowonspark_tpu.feed.prefetch import DevicePrefetcher
    from tensorflowonspark_tpu.models import mnist

    model = mnist.CNN()
    mesh = make_mesh()
    feed = ctx.get_data_feed(
        train_mode=True, input_mapping={"image": "image", "label": "label"}
    )
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((2, 28, 28, 1), np.float32)
    )["params"]
    tx = optax.adam(1e-3)
    state = TrainState.create(params, tx)
    step = build_train_step(mnist.loss_fn(model.apply), tx, mesh)

    def prepare(cols):
        n = len(cols["label"])
        return {
            "image": np.asarray(cols["image"], np.float32).reshape(
                n, 28, 28, 1
            )
            / 255.0,
            "label": np.asarray(cols["label"], np.int32),
        }

    steps = 0
    with DevicePrefetcher.from_feed(
        feed,
        args.batch_size,
        mesh,
        multiple_of=jax.device_count(),
        prepare=prepare,
    ) as pf:
        for batch in pf:
            state, loss = step(state, batch)
            steps += 1
            if steps % 10 == 0:
                print(
                    f"node{ctx.executor_id} step {steps} loss {float(loss):.4f}"
                )
            if steps >= args.target_steps:
                # Early stop: train_stream sees 'terminating' and returns
                # even if the stream is still producing (the prefetcher's
                # close() unblocks its producer thread).
                feed.terminate()
                break
    print(f"node{ctx.executor_id}: trained {steps} streamed steps")


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--micro-batches", type=int, default=20)
    p.add_argument("--records-per-batch", type=int, default=512)
    p.add_argument("--interval", type=float, default=0.2)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--target-steps", type=int, default=30)
    p.add_argument("--cpu", action="store_true")
    return p.parse_args(argv)


if __name__ == "__main__":
    import numpy as np

    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()

    def stream():
        """Micro-batches arriving over time (the DStream)."""
        rng = np.random.default_rng(0)
        for mb in range(args.micro_batches):
            records = [
                (rng.integers(0, 255, size=784), int(rng.integers(0, 10)))
                for _ in range(args.records_per_batch)
            ]
            yield [records]
            time.sleep(args.interval)

    cluster = tfcluster.run(
        main_fun,
        args,
        num_executors=largs["num_executors"],
        input_mode=InputMode.SPARK,
        env=cpu_only_env() if args.cpu else None,
        launcher=largs.get("launcher"),
        distributed=largs.get("distributed", False),
    )
    cluster.train_stream(stream())
    cluster.shutdown()
    print("mnist_streaming done")
