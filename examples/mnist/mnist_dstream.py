"""MNIST training from a DStream — the full Spark Streaming object model.

Reference parity: the pyspark-Streaming examples (``TFCluster.train`` with
a DStream built from ``ssc.textFileStream(HDFS dir)``, SURVEY.md §3.2).
Here :mod:`tensorflowonspark_tpu.streaming` provides the object model: a
``StreamingContext`` watches a directory, each new CSV file becomes one
partition of a micro-batch, a ``map`` parses lines into records, and
``cluster.train(stream)`` feeds them as they arrive. Teardown goes
through ``cluster.shutdown(ssc=ssc)`` like the reference's
``shutdown(ssc)``.

The writer thread below simulates the "new files land in HDFS" side by
dropping CSV shards into the watched directory.

Usage::

    tpu-submit --num-executors 2 examples/mnist/mnist_dstream.py \
        [--files 10] [--rows-per-file 512] [--interval 0.3] [--cpu]
"""

from __future__ import annotations

import os as _os, sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import argparse
import os
import tempfile
import threading
import time

# Workers run the same consumer loop as the generator-based streaming
# example: batch_stream + early terminate after target steps.
from examples.mnist.mnist_streaming import main_fun  # noqa: E402


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dir", default=None, help="directory to watch")
    p.add_argument("--files", type=int, default=10)
    p.add_argument("--rows-per-file", type=int, default=512)
    p.add_argument("--interval", type=float, default=0.3)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--target-steps", type=int, default=20)
    p.add_argument("--cpu", action="store_true")
    return p.parse_args(argv)


def write_files(directory: str, n_files: int, rows: int, interval: float):
    """Simulate files arriving: each is 'label,pix0,...,pix783' CSV rows."""
    import numpy as np

    rng = np.random.default_rng(0)
    for i in range(n_files):
        path = os.path.join(directory, f"part-{i:05d}.csv")
        # dot-prefixed while writing: textFileStream skips hidden names,
        # so the watcher only ever sees the completed file (atomic rename)
        tmp = os.path.join(directory, f".part-{i:05d}.csv.tmp")
        with open(tmp, "w") as f:
            for _ in range(rows):
                label = int(rng.integers(0, 10))
                pixels = rng.integers(0, 255, size=784)
                f.write(f"{label}," + ",".join(map(str, pixels)) + "\n")
        os.rename(tmp, path)
        time.sleep(interval)


def parse_line(line: str):
    import numpy as np

    parts = line.split(",")
    label = int(parts[0])
    image = np.asarray(parts[1:], dtype=np.int64)
    return (image, label)


if __name__ == "__main__":
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.streaming import StreamingContext
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()
    watch_dir = args.dir or tempfile.mkdtemp(prefix="mnist_dstream_")

    cluster = tfcluster.run(
        main_fun,
        args,
        num_executors=largs["num_executors"],
        input_mode=InputMode.SPARK,
        env=cpu_only_env() if args.cpu else None,
        launcher=largs.get("launcher"),
        distributed=largs.get("distributed", False),
    )

    ssc = StreamingContext(batch_interval=max(0.1, args.interval / 2))
    stream = ssc.textFileStream(watch_dir).map(parse_line)
    cluster.train(stream)  # registers the foreachRDD feed bridge
    ssc.start()

    writer = threading.Thread(
        target=write_files,
        args=(watch_dir, args.files, args.rows_per_file, args.interval),
        daemon=True,
    )
    writer.start()
    writer.join()
    time.sleep(2 * args.interval)  # let the last tick deliver

    cluster.shutdown(ssc=ssc)
    print("mnist_dstream done")
