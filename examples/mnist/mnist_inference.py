"""MNIST batch inference through the cluster feed (equal-count contract).

Reference parity: ``examples/mnist/keras/mnist_inference.py`` — feed
records, get one prediction per record, in order.

Usage::

    tpu-submit --num-executors 2 examples/mnist/mnist_inference.py \
        --model-dir /tmp/mnist_model [--cpu]
"""

from __future__ import annotations

import os as _os, sys as _sys

# examples are runnable without installing the package
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))


import argparse


def infer_fun(args, ctx):
    import jax
    import numpy as np

    from tensorflowonspark_tpu.compute.checkpoint import restore_checkpoint
    from tensorflowonspark_tpu.models import mnist

    model = mnist.CNN()
    target = model.init(
        jax.random.PRNGKey(0), np.zeros((2, 28, 28, 1), np.float32)
    )["params"]
    params = restore_checkpoint(args.model_dir, target=target)

    @jax.jit
    def predict(images):
        logits = model.apply({"params": params}, images)
        return jax.numpy.argmax(logits, -1)

    from tensorflowonspark_tpu.feed.prefetch import DevicePrefetcher

    feed = ctx.get_data_feed(train_mode=False)

    def host_batches():
        while not feed.should_stop():
            batch = feed.next_batch(args.batch_size)
            if batch:
                yield batch

    def to_device(batch):
        images = (
            np.stack([np.asarray(r[0], np.float32) for r in batch]).reshape(
                -1, 28, 28, 1
            )
            / 255.0
        )
        return jax.device_put(images)

    # stack + H2D of batch N+1 overlaps predict(batch N) on the device
    with DevicePrefetcher(host_batches(), transform=to_device) as pf:
        for images in pf:
            preds = np.asarray(predict(images))
            feed.batch_results([int(p) for p in preds])


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model-dir", required=True)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--num-records", type=int, default=1024)
    p.add_argument("--cpu", action="store_true")
    return p.parse_args(argv)


if __name__ == "__main__":
    import numpy as np

    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()
    rng = np.random.default_rng(0)
    records = [
        (rng.integers(0, 255, size=784),) for _ in range(args.num_records)
    ]
    cluster = tfcluster.run(
        infer_fun,
        args,
        num_executors=largs["num_executors"],
        input_mode=InputMode.SPARK,
        env=cpu_only_env() if args.cpu else None,
        launcher=largs.get("launcher"),
        distributed=largs.get("distributed", False),
    )
    preds = cluster.inference([records[i::4] for i in range(4)])
    cluster.shutdown()
    print(f"predictions: {len(preds)} records; first 10: {preds[:10]}")
