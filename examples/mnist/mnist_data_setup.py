"""Stage MNIST to TFRecord files.

Reference parity: ``examples/mnist/mnist_data_setup.py`` (staged MNIST to
HDFS as CSV/TFRecords for the other examples). This environment has no
dataset egress, so ``--synthetic`` (default) generates a deterministic fake
MNIST; point ``--from-npz`` at a real ``mnist.npz`` when available.

Usage::

    python examples/mnist/mnist_data_setup.py --output /tmp/mnist_tfr \
        [--num-examples 10000] [--from-npz mnist.npz]
"""

from __future__ import annotations

import os as _os, sys as _sys

# examples are runnable without installing the package
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))


import argparse

import numpy as np


def load_mnist(args) -> tuple[np.ndarray, np.ndarray]:
    if args.from_npz:
        with np.load(args.from_npz) as d:
            return d["x_train"], d["y_train"]
    rng = np.random.default_rng(42)
    images = (rng.random((args.num_examples, 28, 28)) * 255).astype(np.uint8)
    labels = rng.integers(0, 10, size=args.num_examples).astype(np.int64)
    return images, labels


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--output", required=True)
    p.add_argument("--num-examples", type=int, default=10000)
    p.add_argument("--from-npz", default=None)
    p.add_argument("--records-per-file", type=int, default=5000)
    args = p.parse_args()

    from tensorflowonspark_tpu.data import dfutil

    images, labels = load_mnist(args)
    rows = (
        {"image": img.reshape(-1).astype(np.int64), "label": int(lab)}
        for img, lab in zip(images, labels)
    )
    paths = dfutil.saveAsTFRecords(
        rows, args.output, records_per_file=args.records_per_file
    )
    print(f"wrote {len(images)} examples to {len(paths)} files under {args.output}")


if __name__ == "__main__":
    main()
