"""MNIST via the TFEstimator / TFModel pipeline API.

Reference parity: ``examples/mnist/estimator/mnist_spark.py`` +
``pipeline.TFEstimator`` — fit on a record set, then transform.

Usage::

    tpu-submit --num-executors 1 examples/mnist/mnist_estimator.py \
        --export-dir /tmp/mnist_est [--cpu]
"""

from __future__ import annotations

import os as _os, sys as _sys

# examples are runnable without installing the package
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))


import argparse


def train_fn(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh
    from tensorflowonspark_tpu.feed.prefetch import DevicePrefetcher
    from tensorflowonspark_tpu.models import mnist

    model = mnist.MLP(hidden=128)
    mesh = make_mesh()
    feed = ctx.get_data_feed(
        train_mode=True, input_mapping={"image": "image", "label": "label"}
    )
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((2, 784), np.float32)
    )["params"]
    tx = optax.adam(1e-3)
    state = TrainState.create(params, tx)
    step = build_train_step(mnist.loss_fn(model.apply), tx, mesh)

    def prepare(cols):
        return {
            "image": np.asarray(cols["image"], np.float32) / 255.0,
            "label": np.asarray(cols["label"], np.int32),
        }

    with DevicePrefetcher.from_feed(
        feed,
        int(args["batch_size"]),
        mesh,
        multiple_of=jax.device_count(),
        prepare=prepare,
    ) as pf:
        for batch in pf:
            state, _ = step(state, batch)

    ctx.export_saved_model(jax.device_get(state.params), args["export_dir"])


def export_fn(args):
    """(apply_fn, target_state) for TFModel.transform."""
    import jax
    import numpy as np

    from tensorflowonspark_tpu.models import mnist

    model = mnist.MLP(hidden=128)
    target = model.init(
        jax.random.PRNGKey(0), np.zeros((2, 784), np.float32)
    )["params"]

    def apply_fn(params, batch):
        logits = model.apply({"params": params}, batch["x"] / 255.0)
        return {"prediction": jax.numpy.argmax(logits, -1)}

    return apply_fn, target


if __name__ == "__main__":
    import numpy as np

    from tensorflowonspark_tpu.api.pipeline import TFEstimator
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    p = argparse.ArgumentParser()
    p.add_argument("--export-dir", required=True)
    p.add_argument("--num-records", type=int, default=2048)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    largs = cluster_args_from_env()

    rng = np.random.default_rng(0)
    records = [
        (rng.integers(0, 255, size=784), int(rng.integers(0, 10)))
        for _ in range(args.num_records)
    ]

    est = TFEstimator(
        train_fn,
        cluster_size=largs["num_executors"],
        epochs=2,
        batch_size=256,
        export_dir=args.export_dir,
        input_mapping={"image": "image", "label": "label"},
    )
    model = est.fit(
        [records[i::8] for i in range(8)],
        env=cpu_only_env() if args.cpu else None,
    )
    model.export_fn = export_fn
    model.args.input_mapping = {"image": "x"}
    model.args.output_mapping = {"prediction": "pred"}
    # Transform consumes feature-only records: the mapping must name
    # every tuple field in order (feed/datafeed.py's column contract),
    # so strip the labels rather than mapping a 2-field record with one
    # column.
    preds = model.transform([(image,) for image, _label in records[:16]])
    print("sample predictions:", [int(r["pred"]) for r in preds])
