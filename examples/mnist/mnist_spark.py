"""MNIST training, InputMode.SPARK — the canonical push-feed example.

Reference parity: ``examples/mnist/keras/mnist_spark.py`` (DataFeed →
dataset → MultiWorkerMirroredStrategy fit). TPU-native shape: DataFeed →
numpy batches → jit train step on the local device mesh; the chief exports
an orbax checkpoint.

Usage (via the spark-submit-shaped launcher)::

    tpu-submit --num-executors 2 examples/mnist/mnist_spark.py \
        --tfrecords /tmp/mnist_tfr --model-dir /tmp/mnist_model \
        [--epochs 2] [--batch-size 256] [--cpu]
"""

from __future__ import annotations

import os as _os, sys as _sys

# examples are runnable without installing the package
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))


import argparse


def main_fun(args, ctx):
    """Runs on every node (reference: mnist_spark.py:main_fun)."""
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh
    from tensorflowonspark_tpu.feed.prefetch import DevicePrefetcher
    from tensorflowonspark_tpu.models import mnist

    model = mnist.CNN()
    mesh = make_mesh()  # all local devices, data-parallel
    feed = ctx.get_data_feed(
        train_mode=True, input_mapping={"image": "image", "label": "label"}
    )

    params = model.init(
        jax.random.PRNGKey(0), np.zeros((2, 28, 28, 1), np.float32)
    )["params"]
    tx = optax.adam(1e-3)
    state = TrainState.create(params, tx)
    step = build_train_step(mnist.loss_fn(model.apply), tx, mesh)

    # batch_stream re-buffers EndPartition partials into steady jit shapes;
    # the tail is trimmed to a device-count multiple so it still shards.
    # DevicePrefetcher runs prepare + shard/device_put on its producer
    # thread, so batch N+1's columnize+H2D hides behind step N's compute.
    def prepare(cols):
        n = len(cols["label"])
        return {
            "image": np.asarray(cols["image"], np.float32).reshape(n, 28, 28, 1)
            / 255.0,
            "label": np.asarray(cols["label"], np.int32),
        }

    steps = 0
    with DevicePrefetcher.from_feed(
        feed,
        args.batch_size,
        mesh,
        multiple_of=jax.device_count(),
        prepare=prepare,
    ) as pf:
        for batch in pf:
            state, loss = step(state, batch)
            steps += 1
            if steps % 20 == 0:
                print(f"node{ctx.executor_id} step {steps} loss {float(loss):.4f}")

    if args.model_dir and ctx.is_chief:
        ctx.export_saved_model(
            jax.device_get(state.params), args.model_dir
        )
        print(f"chief (node{ctx.executor_id}) exported to {args.model_dir}")


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--tfrecords", default=None, help="TFRecord dir (else synthetic)")
    p.add_argument("--model-dir", default=None)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--num-records", type=int, default=4096)
    p.add_argument("--cpu", action="store_true", help="force CPU-only nodes")
    return p.parse_args(argv)


if __name__ == "__main__":
    import numpy as np

    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()

    if args.tfrecords:
        from tensorflowonspark_tpu.data import dfutil

        records = [
            (np.asarray(r["image"], np.int64), int(r["label"]))
            for r in dfutil.loadTFRecords(args.tfrecords)
        ]
    else:
        rng = np.random.default_rng(0)
        records = [
            (rng.integers(0, 255, size=784), int(rng.integers(0, 10)))
            for _ in range(args.num_records)
        ]

    num_parts = max(4, 2 * largs["num_executors"])
    partitions = [records[i::num_parts] for i in range(num_parts)]

    cluster = tfcluster.run(
        main_fun,
        args,
        num_executors=largs["num_executors"],
        input_mode=InputMode.SPARK,
        env=cpu_only_env() if args.cpu else None,
        launcher=largs.get("launcher"),
        distributed=largs.get("distributed", False),
    )
    cluster.train(partitions, num_epochs=args.epochs)
    cluster.shutdown()
    print("mnist_spark done")
