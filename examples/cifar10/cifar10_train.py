"""CIFAR-10 image classification, InputMode.TENSORFLOW.

Reference parity: ``examples/cifar10`` (SURVEY.md §2.4 "v1-era legacy" —
the multi-GPU-towers CIFAR trainer). TPU-native shape: the towers
disappear into the mesh (DP = batch sharded over ``('data','fsdp')``,
XLA inserts the gradient psum); each node reads its own shard of the
classic CIFAR-10 binary format (1 label byte + 3072 RGB bytes per
record, the same files the reference's ``cifar10_input.py`` consumed).

Usage::

    tpu-submit --num-executors 1 examples/cifar10/cifar10_train.py \
        [--data-dir DIR] [--model resnet18|inception|vit_b16|...] [--steps 200]

Without ``--data-dir`` (no ``data_batch_*.bin`` around), synthetic
CIFAR-shaped data is used so the example runs anywhere.

Checkpoint format: ``{'state': TrainState, 'batch_stats': ...}`` (full
train state, resumable); directories written by the earlier params-only
layout are rejected at startup with a clear error.
"""

from __future__ import annotations

import os as _os, sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import argparse
import glob
import os
import time

RECORD_BYTES = 1 + 32 * 32 * 3  # label byte + HWC uint8 image (binary format)


def _read_cifar_bin(path):
    """Yield (image_hwc_float32, label) from one CIFAR-10 binary batch file."""
    import numpy as np

    raw = np.fromfile(path, np.uint8)
    n = len(raw) // RECORD_BYTES
    recs = raw[: n * RECORD_BYTES].reshape(n, RECORD_BYTES)
    labels = recs[:, 0].astype(np.int32)
    # stored CHW planar; transpose to the TPU-native NHWC
    images = (
        recs[:, 1:].reshape(n, 3, 32, 32).transpose(0, 2, 3, 1).astype(
            np.float32
        )
        / 255.0
    )
    return images, labels


def main_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState
    from tensorflowonspark_tpu.compute.checkpoint import (
        CheckpointManager,
        chief_final_save,
        restore_latest,
    )
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch
    from tensorflowonspark_tpu.models import inception, zoo

    if args.model in ("inception", "inception_v3"):
        # full Inception-v3 is built for 299px; at 32px its aux head
        # pools below zero size, so CIFAR uses the half-width tiny config
        cfg = inception.InceptionConfig.tiny(width_mult=0.5)
        model = inception.InceptionV3(cfg)
        loss_fn = inception.loss_fn(model)
        shardings_of = inception.inception_param_shardings
    else:
        # any image model from the zoo factory (the slim nets_factory
        # surface): resnet18/34/50/101, vgg11/16, vit_b16, ...
        entry = zoo.build(args.model, num_classes=10)
        if entry.kind != "image":
            raise ValueError(
                f"--model {args.model} is a {entry.kind} model; this "
                "example trains image classifiers"
            )
        model = entry.model
        loss_fn = entry.make_loss()
        if not entry.has_batch_stats:
            # stats-less image models (ViT): lift the plain
            # (params, batch) loss into the uniform stats-through
            # signature so one step shape drives every image model
            _plain = loss_fn
            loss_fn = lambda p, bs, b: (_plain(p, b), bs)  # noqa: E731
        shardings_of = entry.param_shardings
    mesh = make_mesh({"data": -1, "fsdp": args.fsdp})
    rng = np.random.default_rng(ctx.executor_id)

    def host_batches():
        files = (
            sorted(glob.glob(os.path.join(args.data_dir, "data_batch_*.bin")))
            if args.data_dir
            else []
        )
        if files:
            # Node i takes every num_workers-th file; with fewer files
            # than nodes, everyone reads all files and shards records.
            shard_records = len(files) < ctx.num_workers
            mine = (
                files
                if shard_records
                else files[ctx.executor_id :: ctx.num_workers]
            )
            while True:
                for f in mine:
                    images, labels = _read_cifar_bin(f)
                    if shard_records:
                        images = images[ctx.executor_id :: ctx.num_workers]
                        labels = labels[ctx.executor_id :: ctx.num_workers]
                    order = rng.permutation(len(labels))
                    for s in range(0, len(order) - args.batch_size + 1, args.batch_size):
                        idx = order[s : s + args.batch_size]
                        yield {"image": images[idx], "label": labels[idx]}
        else:
            while True:
                yield {
                    "image": rng.normal(size=(args.batch_size, 32, 32, 3)).astype(
                        np.float32
                    ),
                    "label": rng.integers(0, 10, size=args.batch_size).astype(
                        np.int32
                    ),
                }

    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((2, 32, 32, 3), np.float32)
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    psh = shardings_of(params, mesh)
    params = jax.tree.map(jax.device_put, params, psh)
    tx = optax.sgd(args.lr, momentum=0.9)
    state = TrainState.create(params, tx)

    ckpt = None
    if args.model_dir:
        # resume-from-latest on every node; only the chief saves
        ckpt = CheckpointManager(ctx.absolute_path(args.model_dir))
        latest, restored = restore_latest(
            ckpt, {"state": state, "batch_stats": batch_stats}
        )
        if latest is not None:
            if ctx.is_chief:
                print(f"resuming from step {latest}")
            state, batch_stats = restored["state"], restored["batch_stats"]

    @jax.jit
    def step(state, batch_stats, batch):
        (l, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch_stats, batch
        )
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(step=state.step + 1, params=new_params, opt_state=new_opt),
            new_bs,
            l,
        )

    batches = host_batches()
    state, batch_stats, l = step(
        state, batch_stats, shard_batch(mesh, next(batches))
    )
    jax.block_until_ready(l)  # compile excluded from timing
    t0 = time.time()
    for _ in range(args.steps):
        state, batch_stats, l = step(
            state, batch_stats, shard_batch(mesh, next(batches))
        )
    jax.block_until_ready(l)
    dt = time.time() - t0
    eps = args.steps * args.batch_size / dt
    print(
        f"node{ctx.executor_id}: {args.steps} steps in {dt:.1f}s -> "
        f"{eps:.1f} examples/sec, loss {float(l):.4f}"
    )

    if args.data_dir and ctx.is_chief:
        test_file = os.path.join(args.data_dir, "test_batch.bin")
        if os.path.exists(test_file):
            images, labels = _read_cifar_bin(test_file)

            @jax.jit
            def logits_of(params, batch_stats, image):
                return model.apply(
                    {"params": params, "batch_stats": batch_stats}, image
                )

            correct = total = 0
            for s in range(0, len(labels) - args.batch_size + 1, args.batch_size):
                lg = logits_of(
                    state.params, batch_stats, images[s : s + args.batch_size]
                )
                correct += int(
                    (np.asarray(lg).argmax(-1) == labels[s : s + args.batch_size]).sum()
                )
                total += args.batch_size
            print(f"test accuracy: {correct / total:.4f} ({total} examples)")

    if ckpt is not None:
        chief_final_save(
            ckpt,
            {"state": state, "batch_stats": batch_stats},
            int(state.step),
            ctx.is_chief,
        )
        if ctx.is_chief:
            print(f"chief checkpointed to {args.model_dir}")


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", default=None, help="dir with data_batch_*.bin")
    p.add_argument(
        "--model",
        default="resnet18",
        help="'inception' (CIFAR-size) or any image model from "
        "models/zoo.py (resnet18/34/50/101, vgg11/16, vit_b16)",
    )
    p.add_argument("--model-dir", default=None)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--fsdp", type=int, default=1, help="fsdp axis size")
    p.add_argument("--cpu", action="store_true")
    return p.parse_args(argv)


if __name__ == "__main__":
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()
    cluster = tfcluster.run(
        main_fun,
        args,
        num_executors=largs["num_executors"],
        input_mode=InputMode.TENSORFLOW,
        env=cpu_only_env() if args.cpu else None,
        launcher=largs.get("launcher"),
        distributed=largs.get("distributed", False),
    )
    cluster.shutdown()
    print("cifar10_train done")
