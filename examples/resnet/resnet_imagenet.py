"""ResNet-50 image classification, InputMode.TENSORFLOW.

Reference parity: the image-classification example trees
(``examples/imagenet/inception``, ``examples/cifar10`` — SURVEY.md §2.4):
each node reads its own shard of the input (no push feed) and trains
data-parallel. TPU-native shape: per-node host pipeline → ``shard_batch``
onto the mesh → jit train step with FSDP param sharding; the chief
checkpoints via orbax.

Usage::

    tpu-submit --num-executors 1 examples/resnet/resnet_imagenet.py \
        [--tfrecords DIR] [--model-dir DIR] [--steps 100] [--tiny] [--cpu]

Without ``--tfrecords``, synthetic ImageNet-shaped data is used (input
pipeline cost ~0, so the number printed is the compute ceiling).
"""

from __future__ import annotations

import os as _os, sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import argparse
import time


def main_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState
    from tensorflowonspark_tpu.compute.checkpoint import (
        CheckpointManager,
        chief_final_save,
        restore_latest,
    )
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch
    from tensorflowonspark_tpu.models import resnet

    cfg = (
        resnet.ResNetConfig.tiny()
        if args.tiny
        else resnet.ResNetConfig.resnet50()
    )
    size = 32 if args.tiny else 224
    model = resnet.ResNet(cfg)
    mesh = make_mesh({"data": -1, "fsdp": args.fsdp})

    rng = np.random.default_rng(ctx.executor_id)

    def host_batches():
        """Per-node input pipeline (the InputMode.TENSORFLOW contract:
        nodes read their own data — reference mnist_tf.py pattern)."""
        if args.tfrecords:
            from tensorflowonspark_tpu.data import dfutil

            # Stream (never materialize the dataset): records carry over
            # epoch boundaries so nothing is dropped and small shards still
            # fill batches across epochs.
            images: list = []
            labels: list = []
            produced = False
            while True:
                for i, r in enumerate(dfutil.loadTFRecords(args.tfrecords)):
                    if i % ctx.num_workers != ctx.executor_id:
                        continue  # shard by node
                    images.append(
                        np.asarray(r["image"], np.float32).reshape(size, size, 3)
                    )
                    labels.append(int(r["label"]))
                    if len(labels) == args.batch_size:
                        produced = True
                        yield {
                            "image": np.stack(images),
                            "label": np.asarray(labels, np.int32),
                        }
                        images, labels = [], []
                if not produced and not labels:
                    raise ValueError(
                        f"no records for node {ctx.executor_id} in "
                        f"{args.tfrecords}"
                    )
        else:
            while True:
                yield {
                    "image": rng.normal(
                        size=(args.batch_size, size, size, 3)
                    ).astype(np.float32),
                    "label": rng.integers(
                        0, cfg.num_classes, size=args.batch_size
                    ).astype(np.int32),
                }

    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((2, size, size, 3), np.float32)
    )
    params, batch_stats = variables["params"], variables["batch_stats"]
    psh = resnet.resnet_param_shardings(params, mesh)
    params = jax.tree.map(jax.device_put, params, psh)
    tx = optax.sgd(0.1, momentum=0.9)
    state = TrainState.create(params, tx)
    loss_fn = resnet.loss_fn(model)

    ckpt = None
    if args.model_dir:
        # every node opens the manager and restores (resume-from-latest,
        # the run_with_restarts recovery convention); only the chief saves
        ckpt = CheckpointManager(ctx.absolute_path(args.model_dir))
        latest, restored = restore_latest(
            ckpt, {"state": state, "batch_stats": batch_stats}
        )
        if latest is not None:
            if ctx.is_chief:
                print(f"resuming from step {latest}")
            state, batch_stats = restored["state"], restored["batch_stats"]

    @jax.jit
    def step(state, batch_stats, batch):
        (l, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch_stats, batch
        )
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt
            ),
            new_bs,
            l,
        )

    batches = host_batches()
    # warmup/compile step excluded from timing
    state, batch_stats, l = step(state, batch_stats, shard_batch(mesh, next(batches)))
    jax.block_until_ready(l)
    t0 = time.time()
    for i in range(args.steps):
        state, batch_stats, l = step(
            state, batch_stats, shard_batch(mesh, next(batches))
        )
    jax.block_until_ready(l)
    dt = time.time() - t0
    eps = args.steps * args.batch_size / dt
    print(
        f"node{ctx.executor_id}: {args.steps} steps in {dt:.1f}s -> "
        f"{eps:.1f} examples/sec ({eps / jax.device_count():.1f} /chip), "
        f"loss {float(l):.4f}"
    )
    if ckpt is not None:
        # the FULL train state (params, optimizer, step) plus the BN
        # batch_stats: a restored model is unusable without its moving
        # statistics, and a resumed run without its optimizer state
        chief_final_save(
            ckpt,
            {"state": state, "batch_stats": batch_stats},
            int(state.step),
            ctx.is_chief,
        )
        if ctx.is_chief:
            print(f"chief checkpointed to {args.model_dir}")


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--tfrecords", default=None)
    p.add_argument("--model-dir", default=None)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--fsdp", type=int, default=1, help="fsdp axis size")
    p.add_argument("--tiny", action="store_true", help="tiny config (CI)")
    p.add_argument(
        "--max-restarts",
        type=int,
        default=0,
        help="supervised whole-cluster auto-restart budget (nodes resume "
        "from --model-dir's latest checkpoint; see run_with_restarts)",
    )
    p.add_argument("--cpu", action="store_true")
    return p.parse_args(argv)


if __name__ == "__main__":
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()
    common = dict(
        num_executors=largs["num_executors"],
        input_mode=InputMode.TENSORFLOW,
        env=cpu_only_env() if args.cpu else None,
        distributed=largs.get("distributed", False),
    )
    if args.max_restarts:
        restarts = tfcluster.run_with_restarts(
            main_fun,
            args,
            max_restarts=args.max_restarts,
            # each attempt needs a fresh launcher; the env-configured one
            # (hosts: mode) is an instance, so rebuild it per attempt
            launcher_factory=(
                (lambda: cluster_args_from_env().get("launcher"))
                if largs.get("launcher") is not None
                else None
            ),
            **common,
        )
        if restarts:
            print(f"recovered after {restarts} restart(s)")
    else:
        cluster = tfcluster.run(main_fun, args, launcher=largs.get("launcher"), **common)
        cluster.shutdown()
    print("resnet_imagenet done")
