"""BERT fine-tune via the TFEstimator / TFModel pipeline, plus AOT export.

Reference parity: the estimator-path examples
(``examples/mnist/estimator/mnist_spark.py`` + ``pipeline.TFEstimator``,
SURVEY.md §2.4/§3.4) applied to the BASELINE.md "BERT-base fine-tune via
the Estimator pipeline" config. Synthetic task: sequence classification
where the label is derivable from token statistics, so loss actually drops.

The fitted model is exported twice: orbax (for TFModel.transform via
``export_fn``) and, on request, an AOT artifact
(:mod:`tensorflowonspark_tpu.api.export`) runnable with zero user code::

    python -m tensorflowonspark_tpu.tools.run_model --export-dir ... --input ...

Usage::

    tpu-submit --num-executors 1 examples/bert/bert_estimator.py \
        --export-dir /tmp/bert_est [--aot-dir /tmp/bert_aot] [--tiny] [--cpu]
"""

from __future__ import annotations

import os as _os, sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import argparse

VOCAB = 64
SEQ = 32
NUM_CLASSES = 2


def _config(tiny: bool):
    from tensorflowonspark_tpu.models.bert import BertConfig

    if tiny:
        return BertConfig.tiny(vocab_size=VOCAB, max_seq_len=SEQ)
    return BertConfig.bert_base(vocab_size=VOCAB, max_seq_len=SEQ)


def make_records(n, seed=0):
    """Token sequences whose label = 1 iff mean(token) > VOCAB/2."""
    import numpy as np

    rng = np.random.default_rng(seed)
    records = []
    for _ in range(n):
        tokens = rng.integers(1, VOCAB, size=SEQ)
        label = int(tokens.mean() > VOCAB / 2)
        records.append((tokens.astype(np.int64), label))
    return records


def train_fn(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState, build_train_step
    from tensorflowonspark_tpu.compute.mesh import make_mesh
    from tensorflowonspark_tpu.feed.prefetch import DevicePrefetcher
    from tensorflowonspark_tpu.models.bert import (
        BertForClassification,
        bert_param_shardings,
        classification_loss_fn,
    )

    cfg = _config(bool(args.get("tiny")))
    model = BertForClassification(config=cfg, num_classes=NUM_CLASSES)
    mesh = make_mesh()
    feed = ctx.get_data_feed(
        train_mode=True, input_mapping={"tokens": "tokens", "label": "label"}
    )
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((2, SEQ), np.int32)
    )["params"]
    psh = bert_param_shardings(params, mesh)
    params = jax.tree.map(jax.device_put, params, psh)
    tx = optax.adamw(float(args.get("lr", 1e-3)))
    state = TrainState.create(params, tx)
    step = build_train_step(
        classification_loss_fn(model), tx, mesh, param_shardings=psh
    )

    def prepare(cols):
        return {
            "tokens": np.asarray(cols["tokens"], np.int32),
            "label": np.asarray(cols["label"], np.int32),
        }

    loss = None
    with DevicePrefetcher.from_feed(
        feed,
        int(args["batch_size"]),
        mesh,
        multiple_of=jax.device_count(),
        prepare=prepare,
    ) as pf:
        for batch in pf:
            state, loss = step(state, batch)
    print(f"node{ctx.executor_id} final loss {float(loss):.4f}")
    ctx.export_saved_model(jax.device_get(state.params), args["export_dir"])

    if ctx.is_chief and args.get("aot_dir"):
        from tensorflowonspark_tpu.api.export import export_model

        def apply_fn(params, batch):
            logits = model.apply({"params": params}, batch["tokens"])
            return {"label": jax.numpy.argmax(logits, -1)}

        export_model(
            apply_fn,
            jax.device_get(state.params),
            {"tokens": np.zeros((2, SEQ), np.int32)},
            ctx.absolute_path(args["aot_dir"]),
            input_mapping={"tokens": "tokens"},
            output_mapping={"label": "prediction"},
        )
        print(f"AOT artifact exported to {args['aot_dir']}")


def export_fn(args):
    """(apply_fn, target_state) for TFModel.transform."""
    import jax
    import numpy as np

    from tensorflowonspark_tpu.models.bert import BertForClassification

    cfg = _config(bool(args.get("tiny")))
    model = BertForClassification(config=cfg, num_classes=NUM_CLASSES)
    target = model.init(
        jax.random.PRNGKey(0), np.zeros((2, SEQ), np.int32)
    )["params"]

    def apply_fn(params, batch):
        logits = model.apply({"params": params}, batch["tokens"].astype("int32"))
        return {"prediction": jax.numpy.argmax(logits, -1)}

    return apply_fn, target


if __name__ == "__main__":
    import numpy as np

    from tensorflowonspark_tpu.api.pipeline import TFEstimator
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    p = argparse.ArgumentParser()
    p.add_argument("--export-dir", required=True)
    p.add_argument("--aot-dir", default=None)
    p.add_argument("--records", type=int, default=2048)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    largs = cluster_args_from_env()

    records = make_records(args.records)
    est = TFEstimator(
        train_fn,
        {
            "export_dir": args.export_dir,
            "aot_dir": args.aot_dir,
            "batch_size": args.batch_size,
            "tiny": args.tiny,
        },
        export_fn=export_fn,
        cluster_size=largs["num_executors"],
        epochs=args.epochs,
        batch_size=args.batch_size,
        export_dir=args.export_dir,
        input_mapping={"tokens": "tokens", "label": "label"},
    )
    model = est.fit(
        records, env=cpu_only_env() if args.cpu else None
    )

    test = make_records(256, seed=1)
    model.args.input_mapping = {"tokens": "tokens", "label": "label"}
    model.args.output_mapping = {"prediction": "prediction"}
    preds = model.transform(test)
    correct = sum(
        int(np.asarray(p["prediction"]).reshape(())) == label
        for p, (_, label) in zip(preds, test)
    )
    print(f"bert_estimator accuracy: {correct}/{len(test)}")
