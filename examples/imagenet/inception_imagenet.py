"""Inception-v3 ImageNet training, InputMode.TENSORFLOW.

Reference parity: ``examples/imagenet/inception`` (SURVEY.md §2.4) — the
model behind the reference's headline "near-linear scalability" chart
(SURVEY.md §6). Per-node host pipeline -> ``shard_batch`` onto the mesh
-> jit train step; aux classifier folded into the loss at 0.4 (the
paper's weight); chief checkpoints via orbax.

Usage::

    tpu-submit --num-executors 1 examples/imagenet/inception_imagenet.py \
        [--tfrecords DIR] [--model-dir DIR] [--steps 50] [--tiny] [--cpu]

Without ``--tfrecords``, synthetic 299x299 data is used (input cost ~0,
so the printed number is the compute ceiling).

Checkpoint format: ``{'state': TrainState, 'batch_stats': ...}`` (full
train state, resumable); directories written by the earlier params-only
layout are rejected at startup with a clear error.
"""

from __future__ import annotations

import os as _os, sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import argparse
import time


def main_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu.compute import TrainState
    from tensorflowonspark_tpu.compute.checkpoint import (
        CheckpointManager,
        chief_final_save,
        restore_latest,
    )
    from tensorflowonspark_tpu.compute.mesh import make_mesh, shard_batch
    from tensorflowonspark_tpu.models import inception

    cfg = (
        inception.InceptionConfig.tiny()
        if args.tiny
        else inception.InceptionConfig.v3()
    )
    size = 64 if args.tiny else 299
    model = inception.InceptionV3(cfg)
    mesh = make_mesh({"data": -1, "fsdp": args.fsdp})
    rng = np.random.default_rng(ctx.executor_id)

    def host_batches():
        if args.tfrecords:
            from tensorflowonspark_tpu.data import dfutil

            images: list = []
            labels: list = []
            produced = False
            while True:
                for i, r in enumerate(dfutil.loadTFRecords(args.tfrecords)):
                    if i % ctx.num_workers != ctx.executor_id:
                        continue  # shard by node
                    images.append(
                        np.asarray(r["image"], np.float32).reshape(size, size, 3)
                    )
                    labels.append(int(r["label"]))
                    if len(labels) == args.batch_size:
                        produced = True
                        yield {
                            "image": np.stack(images),
                            "label": np.asarray(labels, np.int32),
                        }
                        images, labels = [], []
                if not produced and not labels:
                    raise ValueError(
                        f"no records for node {ctx.executor_id} in "
                        f"{args.tfrecords}"
                    )
        else:
            while True:
                yield {
                    "image": rng.normal(
                        size=(args.batch_size, size, size, 3)
                    ).astype(np.float32),
                    "label": rng.integers(
                        0, cfg.num_classes, size=args.batch_size
                    ).astype(np.int32),
                }

    # train=True so the aux head's params exist before the first train step
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((2, size, size, 3), np.float32),
        train=True,
    )
    params, batch_stats = variables["params"], variables["batch_stats"]
    psh = inception.inception_param_shardings(params, mesh)
    params = jax.tree.map(jax.device_put, params, psh)
    tx = optax.sgd(0.045, momentum=0.9)
    state = TrainState.create(params, tx)
    loss_fn = inception.loss_fn(model)

    ckpt = None
    if args.model_dir:
        # resume-from-latest on every node; only the chief saves
        ckpt = CheckpointManager(ctx.absolute_path(args.model_dir))
        latest, restored = restore_latest(
            ckpt, {"state": state, "batch_stats": batch_stats}
        )
        if latest is not None:
            if ctx.is_chief:
                print(f"resuming from step {latest}")
            state, batch_stats = restored["state"], restored["batch_stats"]

    @jax.jit
    def step(state, batch_stats, batch):
        (l, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch_stats, batch
        )
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(step=state.step + 1, params=new_params, opt_state=new_opt),
            new_bs,
            l,
        )

    batches = host_batches()
    state, batch_stats, l = step(
        state, batch_stats, shard_batch(mesh, next(batches))
    )
    jax.block_until_ready(l)  # compile excluded from timing
    t0 = time.time()
    for _ in range(args.steps):
        state, batch_stats, l = step(
            state, batch_stats, shard_batch(mesh, next(batches))
        )
    jax.block_until_ready(l)
    dt = time.time() - t0
    eps = args.steps * args.batch_size / dt
    print(
        f"node{ctx.executor_id}: {args.steps} steps in {dt:.1f}s -> "
        f"{eps:.1f} examples/sec ({eps / jax.device_count():.1f} /chip), "
        f"loss {float(l):.4f}"
    )
    if ckpt is not None:
        chief_final_save(
            ckpt,
            {"state": state, "batch_stats": batch_stats},
            int(state.step),
            ctx.is_chief,
        )
        if ctx.is_chief:
            print(f"chief checkpointed to {args.model_dir}")


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--tfrecords", default=None)
    p.add_argument("--model-dir", default=None)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--fsdp", type=int, default=1, help="fsdp axis size")
    p.add_argument("--tiny", action="store_true", help="tiny config (CI)")
    p.add_argument("--cpu", action="store_true")
    return p.parse_args(argv)


if __name__ == "__main__":
    from tensorflowonspark_tpu.cluster import tfcluster
    from tensorflowonspark_tpu.cluster.tfcluster import InputMode
    from tensorflowonspark_tpu.launcher import cluster_args_from_env
    from tensorflowonspark_tpu.utils.util import cpu_only_env

    args = parse_args()
    largs = cluster_args_from_env()
    cluster = tfcluster.run(
        main_fun,
        args,
        num_executors=largs["num_executors"],
        input_mode=InputMode.TENSORFLOW,
        env=cpu_only_env() if args.cpu else None,
        launcher=largs.get("launcher"),
        distributed=largs.get("distributed", False),
    )
    cluster.shutdown()
    print("inception_imagenet done")
